"""Trace → executable-trace passes: executor claiming, fusion, del insertion.

Reference parity: ``thunder/executors/passes.py`` (
``_transform_for_operator_executor_execution`` :34, ``transform_for_execution``
:136, ``del_last_used`` :290). The claim walk is the same design: each bound
symbol is offered to the executors in priority order; an executor can
substitute its own symbol (with a runtime callable) or rewrite via an
execution transform; unclaimed composites are decomposed into their
subsymbols and re-offered; unclaimed prims fall back to the eager JAX
executor. FusionExecutors then run their fusion passes in list order.
"""

from __future__ import annotations

from thunder_tpu.core.baseutils import check
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, Variable
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx
from thunder_tpu.core.transform_common import dce
from thunder_tpu.core.utils import consumed_vars, produced_vars
from thunder_tpu.executors import Executor, FusionExecutor
from thunder_tpu.observe import decisions as _decisions
from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime import quarantine as _quarantine


_PASSTHROUGH_IDS = (PrimIDs.PYTHON_RETURN, PrimIDs.COMMENT, PrimIDs.PYTHON_DEL,
                    PrimIDs.UNPACK_TRIVIAL)


def _run_execution_transform(transform, bsym: BoundSymbol, trc: TraceCtx) -> list[BoundSymbol]:
    tmp = TraceCtx("exec_transform")
    tmp._names = trc._names  # share the name registry: no collisions
    tmp._counters = trc._counters
    with tracectx(tmp):
        out = transform(*bsym.args, **bsym.kwargs)
    new_flat, _ = tree_flatten(out)
    old_flat, _ = tree_flatten(bsym.output)
    swap = {}
    for n, o in zip(new_flat, old_flat):
        if isinstance(n, Proxy) and isinstance(o, Proxy) and n.name != o.name:
            swap[Variable(n)] = o
    return [b.from_bsym_swap_proxies(swap) for b in tmp.bound_symbols]


def claim_bsym(bsym: BoundSymbol, executors, trc: TraceCtx) -> list[BoundSymbol]:
    if bsym.sym.id in _PASSTHROUGH_IDS or bsym.sym.executor is not None:
        return [bsym]
    log = _decisions.active()  # decision log: one flag read per bsym when off
    for ex in executors:
        if isinstance(ex, FusionExecutor):
            continue  # fusion executors run as whole-trace passes afterwards
        impl = ex.get_impl(bsym)
        if impl is None:
            continue
        # quarantine gate: a claim id that failed at compile/runtime (this
        # process or a previous one — the set persists next to the compile
        # cache) is never offered again; the op falls through to the XLA
        # lowering. ALWAYS recorded in the decision log so explain() answers
        # "why is this op no longer fused".
        claim_id = impl.symbol.id if impl.symbol is not None \
            else f"{ex.name}.{bsym.sym.name}"
        qreason = _quarantine.quarantine_reason(claim_id)
        if qreason is not None:
            # (runtime.fallbacks counts degradation EVENTS at the dispatch
            # layer; counting every per-compile rejection here would inflate
            # the metric with each unrelated recompile)
            if log:
                _decisions.record("claim", bsym.sym.name, ex.name, "rejected",
                                  f"quarantined: {qreason}")
            continue
        if not ex.can_execute(bsym):
            if log:
                _decisions.record("claim", bsym.sym.name, ex.name, "rejected",
                                  "checker refused (shape/dtype/tiling legality)")
            continue
        # cost-model gate: a legal claim may still lose to leaving the op
        # inside an XLA fusion region (memory-bound op, tiny working set).
        # Exceptions fail CLOSED (no claim), mirroring the checker path —
        # a broken cost model must not silently disable the gate
        if impl.profitable is not None:
            try:
                profitable = bool(impl.profitable(bsym))
            except Exception:
                profitable = False
            if not profitable:
                if log:
                    from thunder_tpu.core import cost_model

                    # a broken cost model fails the claim CLOSED (above);
                    # logging its numbers must not resurrect the exception
                    try:
                        flops, nbytes = cost_model.bsym_cost(bsym)
                        cost = {"flops": flops, "bytes": nbytes,
                                "min_claim_bytes": cost_model.MIN_CLAIM_BYTES}
                    except Exception:
                        cost = None
                    _decisions.record(
                        "claim", bsym.sym.name, ex.name, "rejected",
                        "cost model: claim loses to XLA region fusion",
                        cost=cost)
                continue
        if not getattr(ex, "get_fuel", lambda *_: True)():
            if log:
                _decisions.record("claim", bsym.sym.name, ex.name, "rejected",
                                  "optimization fuel exhausted")
            continue
        if impl.execution_transform is not None:
            if log:
                _decisions.record("claim", bsym.sym.name, ex.name, "claimed",
                                  "via execution transform")
            return _run_execution_transform(impl.execution_transform, bsym, trc)
        if impl.symbol is not None:
            if log:
                _decisions.record("claim", bsym.sym.name, ex.name, "claimed")
            claimed = impl.symbol.bind(*bsym.args, output=bsym.output,
                                       subsymbols=bsym.subsymbols, **bsym.kwargs)
            claimed.header = bsym.header  # keep pass annotations (fusion markers)
            return [claimed]
    from thunder_tpu.executors.eagerjax import get_eager_impl

    if bsym.sym.is_prim:
        check(get_eager_impl(bsym.sym) is not None or bsym.sym.python_impl is not None,
              lambda: f"no executor can run prim {bsym.sym.name}")
        if log:
            _decisions.record("claim", bsym.sym.name, "eagerjax", "fallback",
                              "unclaimed prim runs on the eager JAX executor")
        return [bsym]
    if len(bsym.subsymbols) == 0:
        # identity composite (e.g. eval-mode dropout returns its input):
        # every output proxy is an input proxy, so nothing needs emitting —
        # downstream bsyms already reference the producing names
        arg_names = {p.name for p in bsym.flat_proxy_args()}
        outs = bsym.flat_proxy_outs()
        if outs and all(p.name in arg_names for p in outs):
            return []
    check(len(bsym.subsymbols) > 0, lambda: f"unclaimed symbol {bsym.sym.name} has no decomposition")
    if log:
        _decisions.record("claim", bsym.sym.name, None, "decomposed",
                          f"no executor claims the composite; re-offering its "
                          f"{len(bsym.subsymbols)} subsymbols")
    out: list[BoundSymbol] = []
    for sub in bsym.subsymbols:
        out.extend(claim_bsym(sub, executors, trc))
    return out


def transform_for_execution(trc: TraceCtx, executors) -> TraceCtx:
    """Fusion-prep passes + claim pass + fusion passes + DCE (reference
    ``passes.py:136``, extended with the Fusion 2.0 rewrites)."""
    from thunder_tpu.core.fusion_passes import (
        block_fusion_pass,
        epilogue_fusion_pass,
        horizontal_fusion_pass,
        optimizer_fusion_pass,
    )

    # run BEFORE claiming: horizontal merging works on unclaimed dot_generals,
    # and the block/epilogue/optimizer rewrites build composites for the
    # claim walk to offer. The block planner goes FIRST — it wants whole
    # sub-block chains, which horizontal merging (gate+up GEMMs share the
    # normed activation) and epilogue fusion (add→rms_norm) would otherwise
    # carve up. Training traces were already planned pre-autodiff (the chain
    # is prim-level here and the anchor scan early-outs); this entry serves
    # inference traces, whose composite-level chains survive to this pass.
    with _observe.span("block_fusion"):
        trc = block_fusion_pass(trc, executors)
    with _observe.span("horizontal_fusion"):
        trc = horizontal_fusion_pass(trc)
    with _observe.span("epilogue_fusion"):
        trc = epilogue_fusion_pass(trc, executors)
    with _observe.span("optimizer_fusion"):
        trc = optimizer_fusion_pass(trc, executors)

    with _observe.span("claim"):
        ex_bsyms: list[BoundSymbol] = []
        for bsym in trc.bound_symbols:
            ex_bsyms.extend(claim_bsym(bsym, executors, trc))
        new = from_trace(trc)
        new.bound_symbols = ex_bsyms
        new.set_provenance("Executor claim pass")
    from thunder_tpu.core.compile_data import get_compile_option

    # Region annotation happens at CLAIM granularity — before the fusion
    # executors run — because that is the level the decision log speaks at
    # (one planned block / bucketed optimizer chain per claimed bsym). The
    # XLA fusion pass then absorbs the annotated impls into its jax.jit
    # regions, so the named_scope still reaches the lowered HLO metadata and
    # TPU profiler traces attribute time inside fused programs back to the
    # exact verdict. The annotated claim-level trace is kept on the returned
    # trace (``_region_trace``) so observe.profile can replay it region by
    # region on backends without a profiler.
    region_trc = None
    if get_compile_option(
            "region_annotations",
            "wrap each claimed executor callable in a jax.named_scope carrying "
            "its stable region name (executor:symbol#occurrence — the id the "
            "decision log, observe.profile and ProfileTransform share), so "
            "profiler traces attribute time back to compiler verdicts",
            True):
        with _observe.span("annotate_regions"):
            new = region_trc = annotate_regions(new)
    for ex in executors:
        if isinstance(ex, FusionExecutor):
            with _observe.span(f"fusion_pass:{ex.name}"):
                new = ex.fusion_pass(new)
    new = dce(new)
    new.set_provenance("Transform for execution")
    new._region_trace = region_trc
    return new


def annotate_regions(trc: TraceCtx) -> TraceCtx:
    """Thread the stable region names (``observe.profile.region_names_for``
    — the SAME ids the decision log joins on) through dispatch: each bound
    symbol carrying a ``python_impl`` (claimed executor ops, fusion-region
    callables) is rebound to a copy whose impl runs under
    ``jax.named_scope(region_name)``, so the region name lands in the
    lowered HLO op metadata and ``jax.profiler`` traces attribute device
    time back to the exact verdict that scheduled the region."""
    import jax

    from thunder_tpu.observe.profile import region_names_for

    names = region_names_for(trc)
    new = from_trace(trc)
    bsyms: list[BoundSymbol] = []
    for bsym, name in zip(trc.bound_symbols, names):
        if name is None or bsym.sym.python_impl is None:
            bsyms.append(bsym)
            continue
        inner = bsym.sym.python_impl

        def make_impl(_name, _inner):
            def annotated(*args, **kw):
                with jax.named_scope(_name):
                    return _inner(*args, **kw)

            return annotated

        sym = Symbol(bsym.sym.name, bsym.sym.meta, id=bsym.sym.id,
                     is_prim=bsym.sym.is_prim, executor=bsym.sym.executor,
                     python_impl=make_impl(name, inner), tags=bsym.sym.tags)
        bsyms.append(bsym.from_bsym(sym=sym))
    new.bound_symbols = bsyms
    new.set_provenance("Region annotations")
    return new


def del_last_used(trc: TraceCtx) -> TraceCtx:
    """Insert ``del`` statements after each proxy's last use so the eager
    path releases buffers promptly (reference ``passes.py:290``)."""
    from thunder_tpu.core import prims

    out_vars: set[Variable] = set()
    flat_out, _ = tree_flatten(trc.output)
    for o in flat_out:
        if isinstance(o, Proxy):
            out_vars.add(Variable(o))
    arg_vars = {Variable(a) for a in trc.args}

    # only names bound at top level of the generated function may be deleted
    visible: set[Variable] = set(arg_vars)
    for bsym in trc.bound_symbols:
        for p in bsym.flat_proxy_outs():
            visible.add(Variable(p))

    last_use: dict[Variable, int] = {}
    for i, bsym in enumerate(trc.bound_symbols):
        for v in consumed_vars(bsym):
            if v in visible:
                last_use[v] = i

    dels_at: dict[int, list[Proxy]] = {}
    for v, i in last_use.items():
        if v in out_vars or v in arg_vars:
            continue
        dels_at.setdefault(i, []).append(v.proxy)

    new = from_trace(trc)
    bsyms: list[BoundSymbol] = []
    for i, bsym in enumerate(trc.bound_symbols):
        bsyms.append(bsym)
        if i in dels_at and bsym.sym.id is not PrimIDs.PYTHON_RETURN:
            ps = sorted(dels_at[i], key=lambda p: p.name)
            bsyms.append(prims.python_del.bind(*ps, output=None))
    new.bound_symbols = bsyms
    new.set_provenance("Delete last used")
    return new

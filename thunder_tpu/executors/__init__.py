"""Executor framework: prioritized, extensible op claiming + region fusion.

The best idea in the reference (``thunder/extend/__init__.py:56-281``) kept
here: every operation in a trace can be *claimed* by an executor — an
``OperatorExecutor`` substitutes a single bound symbol with an
executor-specific symbol carrying a concrete runtime callable (e.g. a Pallas
flash-attention kernel claiming ``nn.scaled_dot_product_attention``), and a
``FusionExecutor`` groups whole regions into one fused callable (the XLA
executor jax.jit's regions). Executors are consulted in priority order;
the eager-JAX executor is the always-on fallback.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from thunder_tpu.core.baseutils import check
from thunder_tpu.core.symbol import BoundSymbol, Symbol


class ImplInfo:
    """How an executor implements one symbol id.

    ``checker`` answers *can* this executor run the bsym (shape/dtype/tiling
    legality); ``profitable`` answers *should* it (cost-model gate: a legal
    claim may still lose to leaving the op inside an XLA fusion region).
    Both default to yes."""

    __slots__ = ("symbol", "checker", "execution_transform", "grad_transform", "profitable")

    def __init__(self, symbol: Symbol | None = None, checker: Callable | None = None,
                 execution_transform: Callable | None = None, grad_transform: Callable | None = None,
                 profitable: Callable | None = None):
        self.symbol = symbol
        self.checker = checker
        self.execution_transform = execution_transform
        self.grad_transform = grad_transform
        self.profitable = profitable


class Executor:
    # executors that opt in allow the XLA fusion pass to ABSORB their claimed
    # bound symbols into jit regions (the claimed python_impl must be
    # jax-traceable, e.g. a pallas_call): elementwise producers/consumers
    # then fuse around the custom kernel inside one XLA program instead of
    # the claim splitting the region at both kernel boundaries
    fusible_into_regions = False

    def __init__(self, name: str, version: str = "0.1"):
        self.name = name
        self.version = version
        self.implmap: dict[Any, ImplInfo] = {}

    def can_execute(self, bsym: BoundSymbol) -> bool:
        impl = self.implmap.get(bsym.sym.id)
        if impl is None:
            return False
        if impl.checker is not None:
            try:
                return bool(impl.checker(*bsym.args, **bsym.kwargs))
            except Exception:
                return False
        return True

    def get_impl(self, bsym: BoundSymbol) -> ImplInfo | None:
        return self.implmap.get(bsym.sym.id)

    def __repr__(self):
        return f"<Executor {self.name}>"


class OperatorExecutor(Executor):
    """Executor providing per-op runtime callables (reference
    ``thunder/extend/__init__.py:197-279``)."""

    def register_operator(self, name: str, *, meta: Callable | None = None, fn: Callable,
                          like: Symbol | None = None, tags=None) -> Symbol:
        if meta is None and like is not None:
            meta = like.meta
        # every claimed kernel impl runs under the fault-domain guard: it
        # hosts the `kernel:<executor>.<op>` injection domain and attributes
        # failures to the claim id (KernelExecutionError), which is what lets
        # the dispatch layer quarantine exactly this kernel and recompile
        # with the XLA fallback instead of killing the job
        from thunder_tpu.runtime.faults import kernel_guard

        sym_id = f"{self.name}.{name}"
        sym = Symbol(name, meta, id=sym_id, is_prim=True, executor=self,
                     python_impl=kernel_guard(sym_id, fn),
                     tags=tags or (like.tags if like is not None else None))
        return sym

    def register_implementation(self, id_or_sym, op: Symbol | None = None, *,
                                checker: Callable | None = None,
                                execution_transform: Callable | None = None,
                                grad_transform: Callable | None = None,
                                profitable: Callable | None = None) -> None:
        sym_id = id_or_sym.id if isinstance(id_or_sym, Symbol) else id_or_sym
        self.implmap[sym_id] = ImplInfo(symbol=op, checker=checker,
                                        execution_transform=execution_transform,
                                        grad_transform=grad_transform,
                                        profitable=profitable)


class FusionExecutor(Executor):
    """Executor that fuses whole regions of the trace; with optimization-fuel
    debugging as in the reference (``thunder/extend/__init__.py:143-162``)."""

    def __init__(self, name: str, version: str = "0.1"):
        super().__init__(name, version)
        import os

        fuel = os.environ.get(f"{name.upper()}_OPTIMIZATION_FUEL")
        self._fuel = int(fuel) if fuel else None

    def get_fuel(self, amount: int = 1) -> bool:
        if self._fuel is None:
            return True
        if self._fuel < amount:
            return False
        self._fuel -= amount
        return True

    def fusion_pass(self, trace):
        raise NotImplementedError

    def can_fuse(self, bsym: BoundSymbol) -> bool:
        raise NotImplementedError


def single_op_executor(executor_name: str, op_name: str, fn: Callable, *,
                       meta: Callable | None = None, like: Symbol | None = None,
                       checker: Callable | None = None,
                       grad_transform: Callable | None = None,
                       register: bool = True) -> OperatorExecutor:
    """Create an OperatorExecutor claiming exactly one operation — the
    smallest possible custom-kernel integration (reference
    ``thunder/extend/__init__.py:282``).

    ``fn`` is the runtime callable; ``like`` (an existing Symbol, e.g. an op
    from ``thunder_tpu.ops``) supplies the meta and the claimed id.
    """
    ex = OperatorExecutor(executor_name)
    sym = ex.register_operator(op_name, meta=meta, like=like, fn=fn)
    target = like.id if like is not None else op_name
    ex.register_implementation(target, sym, checker=checker, grad_transform=grad_transform)
    if register:
        register_executor(ex)
    return ex


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_executor_map: dict[str, Executor] = {}
_default_executors: list[Executor] = []
_always_executors: list[Executor] = []


def register_executor(ex: Executor, *, default: bool = False, always: bool = False, index: int | None = None):
    _executor_map[ex.name] = ex
    if default and ex not in _default_executors:
        _default_executors.insert(index if index is not None else len(_default_executors), ex)
    if always and ex not in _always_executors:
        _always_executors.append(ex)
    return ex


def get_executor(name: str) -> Executor | None:
    _ensure_builtin_executors()
    return _executor_map.get(name)

def get_all_executors() -> tuple[Executor, ...]:
    _ensure_builtin_executors()
    return tuple(_executor_map.values())


def get_default_executors() -> tuple[Executor, ...]:
    _ensure_builtin_executors()
    return tuple(_default_executors)


def get_always_executors() -> tuple[Executor, ...]:
    _ensure_builtin_executors()
    return tuple(_always_executors)


def resolve_executors(executors: Sequence | None) -> tuple[Executor, ...]:
    if executors is None:
        return get_default_executors()
    out = []
    for e in executors:
        if isinstance(e, Executor):
            out.append(e)
        elif isinstance(e, str):
            ex = get_executor(e)
            check(ex is not None, lambda: f"unknown executor {e!r}; known: {list(_executor_map)}")
            out.append(ex)
        else:
            raise TypeError(f"cannot resolve executor from {e!r}")
    for a in get_always_executors():
        if a not in out:
            out.append(a)
    return tuple(out)


_builtins_loaded = False


def _ensure_builtin_executors():
    """Import built-in executors (registers them). Deferred to avoid import cycles."""
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from thunder_tpu.executors import eagerjax, xla  # noqa: F401

    try:
        from thunder_tpu.executors import pallasex  # noqa: F401
    except Exception:
        pass

"""The XLA fusion executor: lowers regions of the trace to compiled XLA.

This is the nvFuser-executor analog (reference
``thunder/executors/nvfuserex_impl.py``: ``fusion_pass`` :730), rebuilt for
TPU: instead of building FusionDefinitions, each fused region becomes a
``jax.jit``-compiled callable over the region's JAX implementations — XLA
does the kernel fusion, tiling onto MXU/VPU, and layout assignment. Region
callables are cached by jax.jit on input avals (the symbolic-shape region
cache of the reference's ``FusionDefinitionWrapper`` comes for free).

When a fused region executes inside an outer jit/shard_map trace (the
distributed path), the inner jit inlines, so whole-program XLA optimization
still applies.
"""

from __future__ import annotations

from typing import Any

import jax

from thunder_tpu.core.baseutils import check
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, TensorProxy, Variable
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, from_trace
from thunder_tpu.core.utils import consumed_vars, produced_vars
from thunder_tpu.executors import FusionExecutor, register_executor
from thunder_tpu.observe import decisions as _decisions
from thunder_tpu.observe import registry as _observe

_NOFUSE_IDS = {
    PrimIDs.PYTHON_RETURN, PrimIDs.COMMENT, PrimIDs.PYTHON_DEL, PrimIDs.PYTHON_PRINT,
    PrimIDs.SINK, PrimIDs.ITEM, PrimIDs.UNPACK_TRIVIAL, PrimIDs.DEVICE_PUT,
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    PrimIDs.CHECK_STRING_VALUE, PrimIDs.CHECK_LITERAL_LIKE,
}


def _subst(env: dict, x):
    if isinstance(x, Proxy):
        return env[x.name]
    if isinstance(x, tuple):
        return tuple(_subst(env, i) for i in x)
    if isinstance(x, list):
        return [_subst(env, i) for i in x]
    if isinstance(x, dict):
        return {k: _subst(env, v) for k, v in x.items()}
    return x


def _bind(env: dict, out_spec, values):
    flat, _ = tree_flatten(out_spec)
    vflat, _ = tree_flatten(values)
    for o, v in zip(flat, vflat):
        if isinstance(o, Proxy):
            env[o.name] = v


def run_bsyms(bsyms, env: dict):
    """Interpret a bsym sequence over concrete (or tracer) values."""
    from thunder_tpu.executors.eagerjax import get_eager_impl

    for b in bsyms:
        if b.sym.id in (PrimIDs.COMMENT, PrimIDs.PYTHON_DEL, PrimIDs.PYTHON_RETURN):
            continue
        impl = b.sym.python_impl or get_eager_impl(b.sym)
        if impl is None:
            check(len(b.subsymbols) > 0, lambda: f"cannot execute {b.sym.name}")
            run_bsyms(b.subsymbols, env)
            continue
        out = impl(*_subst(env, b.args), **_subst(env, b.kwargs))
        _bind(env, b.output, out)


class XLAFusionExecutor(FusionExecutor):
    """Greedy contiguous-region fusion; each region is jax.jit compiled."""

    def __init__(self, name: str = "xla", min_region_size: int = 2):
        super().__init__(name)
        self.min_region_size = min_region_size

    def can_fuse(self, bsym: BoundSymbol) -> bool:
        if bsym.sym.id in _NOFUSE_IDS:
            return False
        if OpTags.DEVICE_SYNC_OP in bsym.sym.tags:
            return False
        # ops claimed by another executor (e.g. Pallas kernels) stay out of
        # fusion regions, exactly like cudnn-claimed ops stay outside nvFuser
        # regions in the reference (thunder/executors/passes.py:136 ordering)
        # — unless the claiming executor opts into absorption (can_absorb)
        if bsym.sym.executor is not None and bsym.sym.executor is not self:
            return False
        if bsym.sym.python_impl is not None:
            return True
        from thunder_tpu.executors.eagerjax import get_eager_impl

        return get_eager_impl(bsym.sym) is not None

    def can_absorb(self, bsym: BoundSymbol) -> bool:
        """Can this claimed-by-another-executor bsym be ABSORBED into an XLA
        fusion region? Yes when the claiming executor opted in
        (``fusible_into_regions`` — its impls are jax-traceable, e.g.
        pallas_calls): the custom kernel then runs *inside* the region's
        jax.jit, so XLA fuses elementwise producers/consumers around it
        instead of the claim splitting the region at both kernel boundaries
        (an HBM round-trip per boundary). Sync/collective ops never absorb."""
        if bsym.sym.executor is None or bsym.sym.executor is self:
            return False
        if bsym.sym.id in _NOFUSE_IDS:
            return False
        if OpTags.DEVICE_SYNC_OP in bsym.sym.tags or OpTags.COLLECTIVE_OP in bsym.sym.tags:
            return False
        if not getattr(bsym.sym.executor, "fusible_into_regions", False):
            return False
        return bsym.sym.python_impl is not None

    def fusion_pass(self, trc: TraceCtx) -> TraceCtx:
        from thunder_tpu.core.compile_data import get_compile_option

        if get_compile_option("xla_disable_fusion",
                              "skip XLA region fusion entirely (all ops run eagerly); "
                              "bisection/debugging aid", False):
            return trc
        min_region_size = get_compile_option(
            "xla_min_region_size",
            "minimum bound symbols per XLA fusion region; smaller regions stay eager",
            self.min_region_size)
        partitioner = get_compile_option(
            "xla_partitioner",
            "fusion region formation: 'dataflow' (data-dependent partitioner — "
            "maximal regions under the dataflow graph, reference "
            "data_dependent_partition.py) or 'contiguous' (greedy program-order runs)",
            "dataflow")
        absorb_claimed = get_compile_option(
            "xla_absorb_claimed",
            "absorb claimed custom kernels (pallas) INTO XLA fusion regions instead of "
            "splitting regions around them — elementwise epilogues then fuse with the "
            "kernel's inputs/outputs inside one XLA program", True)
        # outputs of the whole trace stay live
        live_out = {Variable(o) for o in tree_flatten(trc.output)[0] if isinstance(o, Proxy)}

        def fusible(bsym: BoundSymbol) -> bool:
            return (self.can_fuse(bsym)
                    or (absorb_claimed and self.can_absorb(bsym))) and self.get_fuel()

        # fuel consumption must be deterministic per bsym: memoize once and
        # use the same answers for grouping AND emission (a fuel-denied bsym
        # must stay unfused on every path — fuel bisection depends on it)
        fuel_ok = {id(b): fusible(b) for b in trc.bound_symbols}

        groups: list[list[BoundSymbol]]
        if partitioner == "dataflow":
            from thunder_tpu.executors.data_dependent_partition import fuse_bound_symbols

            groups = fuse_bound_symbols(trc.bound_symbols, lambda b: fuel_ok[id(b)])
        else:
            groups = []
            current: list[BoundSymbol] = []
            for bsym in trc.bound_symbols:
                if fuel_ok[id(bsym)]:
                    current.append(bsym)
                else:
                    if current:
                        groups.append(current)
                        current = []
                    groups.append([bsym])
            if current:
                groups.append(current)

        new = from_trace(trc)
        new_bsyms: list[BoundSymbol] = []
        # for group i: vars consumed by groups after i (region outputs)
        suffix_consumed: set[Variable] = set(live_out)
        suffix_sets: list[set[Variable]] = [set()] * len(groups)
        for i in range(len(groups) - 1, -1, -1):
            suffix_sets[i] = set(suffix_consumed)
            for b in groups[i]:
                suffix_consumed |= consumed_vars(b)

        for i, gbsyms in enumerate(groups):
            if len(gbsyms) < min_region_size or not all(fuel_ok[id(b)] for b in gbsyms):
                new_bsyms.extend(gbsyms)
                continue
            new_bsyms.append(self._make_fusion_bsym(gbsyms, suffix_sets[i], new))
        new.bound_symbols = new_bsyms
        new.set_provenance("XLA fusion pass")
        return new

    def _make_fusion_bsym(self, gbsyms: list[BoundSymbol], needed_later: set[Variable],
                          trc: TraceCtx) -> BoundSymbol:
        produced: set[Variable] = set()
        inputs: list[Proxy] = []
        seen_in: set[str] = set()
        for b in gbsyms:
            for v in sorted(consumed_vars(b), key=lambda v: v.proxy.name):
                if v not in produced and v.proxy.name not in seen_in:
                    seen_in.add(v.proxy.name)
                    inputs.append(v.proxy)
            produced |= produced_vars(b)
        outputs = [v.proxy for v in produced if v in needed_later]
        outputs.sort(key=lambda p: p.name)
        input_names = [p.name for p in inputs]
        output_names = [p.name for p in outputs]

        def region_fn(*vals):
            env = dict(zip(input_names, vals))
            run_bsyms(gbsyms, env)
            return tuple(env[n] for n in output_names)

        jitted = jax.jit(region_fn)
        idx = trc.fused_index
        trc.fused_index += 1
        sym = Symbol(f"fusion{idx}", None, id=f"xla.fusion{idx}", is_prim=True,
                     executor=self, python_impl=jitted)
        bsym = sym.bind(*inputs, output=tuple(outputs), subsymbols=list(gbsyms))
        _observe.inc("fusion.xla_regions")
        if _decisions.active():
            from thunder_tpu.core import cost_model

            # logging the region's cost numbers must not resurrect a
            # cost-model exception and abort the compile
            try:
                flops, nbytes = cost_model.region_cost(gbsyms)
                cost = {"ops": len(gbsyms), "flops": flops, "boundary_bytes": nbytes,
                        "memory_bound": cost_model.is_memory_bound(flops, nbytes)}
            except Exception:
                cost = {"ops": len(gbsyms)}
            _decisions.record(
                "fusion", f"xla.fusion{idx}", self.name, "fused",
                f"{len(gbsyms)} ops into one jax.jit region", cost=cost)
        notes = []
        absorbed = [b.sym.codegen_name() for b in gbsyms
                    if b.sym.executor is not None and b.sym.executor is not self]
        if absorbed:
            notes.append("absorbs " + ", ".join(absorbed))
        # surface member annotations (horizontal-fusion / epilogue-fusion
        # markers) on the region: the generated program is the only trace
        # most users read, and the members are hidden in subsymbols
        notes.extend(b.header for b in gbsyms if b.header)
        if notes:
            bsym.header = "\n".join(notes)
        return bsym


ex = XLAFusionExecutor()
register_executor(ex, default=True)

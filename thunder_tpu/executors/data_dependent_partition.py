"""Data-dependent fusion partitioning.

Reference parity: ``thunder/executors/data_dependent_partition.py`` — a
dataflow ``Graph`` over bound symbols (:79), iterative ``dataflow_merge``
(:213) and ``horizontal_merge`` (:252) with cycle avoidance, and
``fuse_bound_symbols(trace, merge_fn)`` (:300) returning ordered groups.

Why not just fuse contiguous runs: an unfusible op in *program order* (a
Pallas-claimed kernel, an ITEM sync, a COMMENT) does not necessarily sit on
the *dataflow* path between its neighbours — contiguous grouping would split
one legal fusion region into two. Here regions are maximal under dataflow:
two fusible ops land in one group unless merging them would create a cycle
through a non-member (which would make the region's inputs depend on its own
outputs).
"""

from __future__ import annotations

from typing import Callable, Sequence

from thunder_tpu.core.symbol import BoundSymbol
from thunder_tpu.core.utils import consumed_vars, produced_vars


class Node:
    """A mergeable group of bound symbols (starts as a single bsym)."""

    __slots__ = ("bsyms", "parents", "children", "min_index", "max_index", "order")

    def __init__(self, bsym: BoundSymbol, index: int):
        self.bsyms: list[BoundSymbol] = [bsym]
        self.parents: set[Node] = set()
        self.children: set[Node] = set()
        self.min_index = index
        self.max_index = index
        self.order: dict[int, int] = {id(bsym): index}  # program order of members

    def __repr__(self):
        return f"<Node {[b.sym.name for b in self.bsyms]}>"


class Graph:
    """Dataflow graph over a trace's bound symbols (reference ``Graph`` :79)."""

    def __init__(self, bsyms: Sequence[BoundSymbol]):
        self.nodes: list[Node] = [Node(b, i) for i, b in enumerate(bsyms)]
        # recursive consumed/produced (like the fusion pass's region-IO
        # computation): a composite whose SUBSYMBOLS read a proxy absent from
        # its top-level args still depends on that proxy's producer
        producer_of: dict[str, Node] = {}
        for n in self.nodes:
            for b in n.bsyms:
                for v in produced_vars(b):
                    producer_of[v.proxy.name] = n
        for n in self.nodes:
            for b in n.bsyms:
                for v in consumed_vars(b):
                    p = producer_of.get(v.proxy.name)
                    if p is not None and p is not n:
                        n.parents.add(p)
                        p.children.add(n)

    def _reachable(self, src: Node, dst: Node, *, skip_direct: bool) -> bool:
        """Is there a path src -> dst (optionally ignoring the direct edge)?

        Pure DFS — no index-based pruning: once nodes merge, a node can be
        entered via a high-program-index member and exited via a low-index
        one, so member-index bounds cannot soundly prune paths (an earlier
        pruned version produced cycles under fuzzing).
        """
        stack = [c for c in src.children if not (skip_direct and c is dst)]
        seen: set[int] = set()
        while stack:
            n = stack.pop()
            if n is dst:
                return True
            if id(n) in seen:
                continue
            seen.add(id(n))
            stack.extend(n.children)
        return False

    def merge(self, a: Node, b: Node) -> Node:
        """Fold ``b`` into ``a`` (bsyms kept in program order)."""
        a.order.update(b.order)
        a.bsyms = sorted(a.bsyms + b.bsyms, key=lambda bs: a.order[id(bs)])
        a.min_index = min(a.min_index, b.min_index)
        a.max_index = max(a.max_index, b.max_index)
        for p in b.parents:
            p.children.discard(b)
            if p is not a:
                p.children.add(a)
                a.parents.add(p)
        for c in b.children:
            c.parents.discard(b)
            if c is not a:
                c.parents.add(a)
                a.children.add(c)
        a.parents.discard(b)
        a.children.discard(b)
        a.parents.discard(a)
        a.children.discard(a)
        self.nodes.remove(b)
        return a

    def dataflow_merge(self, can_merge: Callable[[Node, Node], bool]) -> None:
        """Merge producer->consumer pairs until fixpoint (reference :213).
        A pair is mergeable when ``can_merge`` allows it and no *other* path
        connects them (merging would otherwise create a cycle)."""
        changed = True
        while changed:
            changed = False
            for n in list(self.nodes):
                if n not in self.nodes:
                    continue
                for c in sorted(n.children, key=lambda x: x.min_index):
                    if not can_merge(n, c):
                        continue
                    if self._reachable(n, c, skip_direct=True):
                        continue  # indirect path through a non-member: cycle
                    self.merge(n, c)
                    changed = True
                    break

    def horizontal_merge(self, can_merge: Callable[[Node, Node], bool]) -> None:
        """Merge sibling nodes (no path either way) that share a parent or
        are both roots (reference :252) — catches parallel branches that the
        vertical pass cannot join."""
        changed = True
        while changed:
            changed = False
            groups: list[list[Node]] = []
            roots = [n for n in self.nodes if not n.parents]
            if len(roots) > 1:
                groups.append(roots)
            for n in self.nodes:
                if len(n.children) > 1:
                    groups.append(sorted(n.children, key=lambda x: x.min_index))
            for group in groups:
                for i in range(len(group)):
                    for j in range(i + 1, len(group)):
                        a, b = group[i], group[j]
                        if a not in self.nodes or b not in self.nodes or a is b:
                            continue
                        if not can_merge(a, b):
                            continue
                        if self._reachable(a, b, skip_direct=False) or \
                                self._reachable(b, a, skip_direct=False):
                            continue
                        self.merge(a, b)
                        changed = True
                if changed:
                    break

    def toposorted(self) -> list[Node]:
        """Topological order, stable by minimum original index."""
        indeg = {id(n): len(n.parents) for n in self.nodes}
        import heapq

        ready = [(n.min_index, id(n), n) for n in self.nodes if not n.parents]
        heapq.heapify(ready)
        out: list[Node] = []
        while ready:
            _, _, n = heapq.heappop(ready)
            out.append(n)
            for c in n.children:
                indeg[id(c)] -= 1
                if indeg[id(c)] == 0:
                    heapq.heappush(ready, (c.min_index, id(c), c))
        if len(out) != len(self.nodes):  # pragma: no cover - cycle guard
            raise RuntimeError("partition graph has a cycle")
        return out


def fuse_bound_symbols(bsyms: Sequence[BoundSymbol],
                       fusible: Callable[[BoundSymbol], bool]) -> list[list[BoundSymbol]]:
    """Partition ``bsyms`` into an ordered list of groups: maximal fusible
    regions under dataflow plus singleton groups for unfusible ops
    (reference ``fuse_bound_symbols`` :300). Within each group, bsyms keep
    program order; groups come out topologically sorted."""
    g = Graph(bsyms)
    node_fusible = {id(n): fusible(n.bsyms[0]) for n in g.nodes}

    def can_merge(a: Node, b: Node) -> bool:
        # merged nodes only ever contain fusible members, so the per-node
        # flag (cached at creation, AND-ed on merge by construction) suffices
        return node_fusible[id(a)] and node_fusible[id(b)]

    g.dataflow_merge(can_merge)
    g.horizontal_merge(can_merge)
    return [n.bsyms for n in g.toposorted()]

"""Supervised serving-engine lifecycle: crash recovery, graceful drain,
and a stall watchdog.

The paper's core discipline — every fast path gets an always-available
fallback rung — extended one level up: the *engine itself* is the fast
path here, and the fallback rung is a supervised restart. PR 7/8 gave
training this story (fault domains, retry budgets, quarantine, the
numerics sentinel); :class:`EngineSupervisor` is the serving counterpart:

- **Crash recovery.** When a dispatch fault consumes the donated page
  pools mid-execution, the engine's retry classifier escalates FATAL and
  the scheduler raises :class:`~thunder_tpu.serving.errors.EngineFault`.
  The supervisor rebuilds the pools and the decode binding
  (:meth:`ServingEngine.rebuild_after_fault`) and re-admits every
  in-flight request by re-prefilling prompt + generated tokens — PR 10's
  recompute-on-resume discipline generalized from *preemption* to *crash*
  recovery, so surviving outputs stay token-identical to a fault-free run.
- **Restart budget.** Each restart charges a
  :class:`~thunder_tpu.runtime.retry.RestartBudget` sliding window; an
  engine failing faster than restarts can honestly mask escalates
  :class:`~thunder_tpu.serving.errors.RestartBudgetExceeded` to the
  caller instead of flapping forever.
- **Graceful drain/shutdown.** :meth:`drain` stops admissions (later
  ``submit()`` raises ``AdmissionRejected``), finishes residents under an
  optional wall-clock bound (expiry sheds the rest with
  ``DeadlineExceeded``), and records the whole episode in the
  ``serving.drain_ms`` histogram.
- **Stall watchdog.** With ``heartbeat_path=`` set, every :meth:`step`
  publishes a heartbeat and an :class:`~thunder_tpu.elastic.Watchdog`
  thread escalates when it goes stale — a dispatch hung inside the device
  never raises, but its heartbeat age climbs
  (``runtime.heartbeat_age_s``) and ``on_stall`` fires instead of the
  engine hanging forever unobserved.
- **statusz.** With ``statusz_dir=`` set, :meth:`step` also writes an
  atomic per-engine JSON status snapshot (same tmp+rename discipline as
  the heartbeat, throttled to ``statusz_interval_s``): engine vitals plus
  the health verdict when a :class:`~thunder_tpu.serving.health
  .FleetObservatory` attached one. A directory of these files IS the
  fleet's cross-process view (``FleetObservatory.aggregate_statusz``).

>>> sup = EngineSupervisor(engine, max_restarts=3, restart_window_s=600.0)
>>> req = sup.submit(prompt, max_new_tokens=32, deadline_s=30.0)
>>> sup.drain(deadline_s=120.0)   # stop admissions, finish residents
>>> sup.shutdown()
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable

from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime import retry as _retry
from thunder_tpu.serving.errors import (
    EngineFault,
    EngineStallError,
    RestartBudgetExceeded,
)
from thunder_tpu.serving.scheduler import Request, ServingEngine


class EngineSupervisor:
    """Wraps a :class:`ServingEngine` with the restart/drain/watchdog
    lifecycle. All request traffic should flow through the supervisor
    (``submit``/``step``/``drain``) so faults recover transparently.

    With ``postmortem_dir=`` set, every typed serving failure —
    ``EngineFault`` (even when the restart rung recovers it),
    ``EngineStallError``, ``RestartBudgetExceeded``, and an SLO-attainment
    collapse below ``slo_floor`` — dumps a **postmortem bundle**: the
    always-on flight-recorder ring (the request-lifecycle black box, alive
    even with the registry disabled), the decode program's decision log, a
    registry snapshot, the engine/cache state summary
    (:meth:`ServingEngine.describe_state`, including the
    ``assert_quiescent`` findings and block-table occupancy), the restart
    budget's ``describe()``, and the Perfetto serving timeline
    (``timeline.json`` — built from the flight ring, loadable at
    chrome://tracing). The PR 8 replay-bundle discipline, generalized from
    numerics to serving."""

    def __init__(self, engine: ServingEngine, *,
                 restart_budget: _retry.RestartBudget | None = None,
                 max_restarts: int = 3, restart_window_s: float = 600.0,
                 heartbeat_path: str | None = None,
                 stall_timeout_s: float = 30.0,
                 on_stall: Callable[[float], None] | None = None,
                 postmortem_dir: str | None = None,
                 slo_floor: float | None = None, min_slo_samples: int = 8,
                 statusz_dir: str | None = None,
                 statusz_interval_s: float = 1.0):
        self.engine = engine
        # all supervisor emissions carry the supervised engine's label —
        # fleet aggregation keys on it
        self._obs = engine.obs
        self.budget = restart_budget or _retry.RestartBudget(
            max_restarts=max_restarts, window_s=restart_window_s)
        self.restarts = 0
        self.on_stall = on_stall
        self.postmortem_dir = postmortem_dir
        # attached by FleetObservatory.add(); stays None when unsupervised
        # by a fleet plane (statusz payloads then carry health: None)
        self.health = None
        self.statusz = None
        if statusz_dir is not None:
            from thunder_tpu.observe import statusz as _statusz

            self.statusz = _statusz.StatusWriter(
                statusz_dir, engine.engine_id,
                interval_s=statusz_interval_s)
        self.slo_floor = slo_floor
        self.min_slo_samples = int(min_slo_samples)
        self._slo_collapsed = False     # latched: one bundle per collapse
        # (attained, total, engine reset generation) at last (re)arm — the
        # generation detects reset_slo_window() even when the counters have
        # regrown past the base by the next check (totals alone can't).
        # Armed from the engine's CURRENT counters: attaching to a warm
        # engine must not judge pre-supervisor history
        self._slo_base = (engine._slo_attained, engine._slo_total,
                          engine._slo_resets)
        self.heartbeat = None
        self.watchdog = None
        if heartbeat_path is not None:
            from thunder_tpu.elastic import Heartbeat, Watchdog

            self.heartbeat = Heartbeat(heartbeat_path)
            self.watchdog = Watchdog(heartbeat_path, stall_timeout_s,
                                     escalate=self._escalate_stall).start()

    # -- request traffic ----------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, **kwargs) -> Request:
        """Delegates to the engine (draining engines raise
        ``AdmissionRejected`` there — one admission gate, not two)."""
        return self.engine.submit(prompt, max_new_tokens, **kwargs)

    def step(self) -> bool:
        """One supervised engine iteration: publish the heartbeat, run the
        engine step, and turn an ``EngineFault`` into a budget-charged
        restart instead of a crash. Returns whether progress was made
        (a restart counts — recovery IS progress)."""
        if self.heartbeat is not None:
            self.heartbeat.beat(self.engine._step_count)
        if self.statusz is not None:
            self.statusz.maybe_write(self.status_payload())
        try:
            worked = self.engine.step()
        except EngineFault as e:
            # black box FIRST, while the engine still shows the crashed
            # state (consumed pools, stranded residents) — then recover
            self.dump_postmortem(e)
            self._restart(e)
            return True
        self._check_slo()
        return worked

    def status_payload(self) -> dict:
        """The /statusz snapshot body: cheap per-step engine vitals (no
        ``describe_state`` — that audits quiescence; this is a heartbeat
        with content). Health state rides along when a fleet plane
        attached an :class:`~thunder_tpu.serving.health.EngineHealth`."""
        eng = self.engine
        return {
            "step": eng._step_count,
            "admitting": eng.admitting,
            "queue_depth": len(eng.queue),
            "max_queue": eng.max_queue,
            "active_requests": eng.active_requests,
            "pages_free": eng.cache.pages_free,
            "pages_total": eng.cache.pages_total,
            "completed": len(eng.completed),
            "shed": len(eng.shed),
            "slo_attained": eng._slo_attained,
            "slo_total": eng._slo_total,
            "decode_rebinds": eng.decode_rebinds,
            "restarts": self.restarts,
            "budget": self.budget.describe(),
            "health": (self.health.state if self.health is not None else None),
        }

    def drain(self, *, deadline_s: float | None = None,
              max_steps: int = 1_000_000) -> list[Request]:
        """Graceful drain: stop admissions, then run residents and queued
        requests to completion under ``deadline_s`` (wall clock). On bound
        expiry the remainder is shed with ``DeadlineExceeded``; a
        no-progress step raises ``EngineStallError`` (same contract as
        ``ServingEngine.drain``, but each step here is supervised, so an
        engine fault mid-drain restarts and keeps draining). Records the
        episode in ``serving.drain_ms`` and returns the completed list."""
        eng = self.engine
        eng.stop_admissions()
        t0 = time.perf_counter()
        t0_us = _observe._now_us()
        try:
            for _ in range(max_steps):
                if eng.idle:
                    break
                if deadline_s is not None and \
                        time.perf_counter() - t0 > deadline_s:
                    victims = eng.shed_outstanding(
                        f"drain wall-clock bound ({deadline_s}s) expired")
                    self._obs.event("serving_drain_bound_expired",
                                   shed=[r.request_id for r in victims])
                    break
                if not self.step():
                    raise eng._stall_error("no-progress step during drain")
            else:
                if not eng.idle:
                    raise eng._stall_error(
                        f"no completion in {max_steps} drain steps")
        except EngineStallError as e:
            self.dump_postmortem(e)     # a stall IS the black-box case
            raise
        finally:
            self._obs.observe_value("serving.drain_ms",
                                   (time.perf_counter() - t0) * 1e3)
            # the drain episode on the scheduler track, next to its steps
            self._obs.record_span("drain", "serving:sched", t0_us,
                                 _observe._now_us() - t0_us,
                                 {"completed": len(eng.completed),
                                  "shed": len(eng.shed)})
        return eng.completed

    def shutdown(self, *, deadline_s: float | None = None) -> list[Request]:
        """Drain (bounded when ``deadline_s`` is given), then stop the
        watchdog thread. Terminal: the engine stays non-admitting."""
        try:
            return self.drain(deadline_s=deadline_s)
        finally:
            self.close()

    def close(self) -> None:
        """Stop the watchdog thread (idempotent). Does not drain. Flushes
        a final statusz snapshot so the terminal state is on disk."""
        if self.statusz is not None:
            try:
                self.statusz.write(self.status_payload())
            except Exception:
                pass
        if self.watchdog is not None:
            self.watchdog.stop()

    # -- recovery internals -------------------------------------------------
    def _escalate_stall(self, age_s: float) -> None:
        self._obs.event("serving_engine_stalled", age_s=age_s,
                       step=self.engine._step_count)
        # a hung engine is the paradigm black-box case: dump the ring
        # before the operator kills the process and it's gone (the
        # watchdog escalates once per stall episode, so this is one
        # bundle per stall, not one per poll)
        self.dump_postmortem(
            RuntimeError(f"engine stalled: heartbeat {age_s:.1f}s old at "
                         f"step {self.engine._step_count}"), tag="stall")
        if self.on_stall is not None:
            self.on_stall(age_s)

    def _check_slo(self) -> None:
        """SLO-attainment collapse detector: when the on-time ratio over
        terminal requests SINCE THE LAST (RE)ARM falls below ``slo_floor``
        (with at least ``min_slo_samples`` terminals in that window), the
        black box dumps once — silent degradation is the failure mode a
        flight recorder exists for. Latched until :meth:`rearm_slo` (one
        bundle per collapse, not one per step); the windowing means a
        rearm after mitigation starts a FRESH measurement instead of
        re-judging the historical misses that caused the first dump."""
        if self.slo_floor is None or self._slo_collapsed:
            return
        eng = self.engine
        base_a, base_t, base_gen = self._slo_base
        if eng._slo_resets != base_gen:  # engine's window was reset under us
            self._slo_base = (0, 0, eng._slo_resets)
            base_a, base_t = 0, 0
        total = eng._slo_total - base_t
        # the max() also guards min_slo_samples=0 ("judge immediately")
        # against a 0/0 before the first terminal request
        if total < max(self.min_slo_samples, 1):
            return
        ratio = (eng._slo_attained - base_a) / total
        if ratio < self.slo_floor:
            self._slo_collapsed = True
            self._obs.event("serving_slo_collapse", attainment=round(ratio, 4),
                           floor=self.slo_floor, samples=total)
            self.dump_postmortem(
                RuntimeError(f"SLO attainment collapsed: {ratio:.3f} < floor "
                             f"{self.slo_floor:g} over {total} "
                             f"terminal requests"), tag="slo_collapse")

    def rearm_slo(self) -> None:
        """Un-latch the SLO-collapse detector after mitigation and start a
        fresh measurement window (past misses are not re-judged)."""
        self._slo_collapsed = False
        eng = self.engine
        self._slo_base = (eng._slo_attained, eng._slo_total, eng._slo_resets)

    def dump_postmortem(self, cause: BaseException | str,
                        tag: str | None = None) -> str | None:
        """Write the black-box bundle for ``cause`` under
        ``postmortem_dir`` (no-op returning ``None`` when unset). Never
        raises — a postmortem failure must not break the recovery path it
        documents; partial bundles record their errors in the manifest."""
        if self.postmortem_dir is None:
            return None
        from thunder_tpu.observe import exporters as _exporters
        from thunder_tpu.observe import flight as _flight

        label = tag or (type(cause).__name__
                        if isinstance(cause, BaseException) else "incident")
        try:
            base = os.path.join(
                self.postmortem_dir,
                f"postmortem-step{self.engine._step_count:06d}-{label}")
            path, i = base, 1
            while os.path.exists(path):
                path = f"{base}.{i}"
                i += 1
            os.makedirs(path)
        except Exception:
            return None
        errors: list[str] = []

        def part(fname: str, build) -> None:
            try:
                obj = build()
                with open(os.path.join(path, fname), "w") as f:
                    json.dump(_exporters._jsonable(obj), f, default=str)
            except Exception as e:  # partial bundle beats no bundle
                errors.append(f"{fname}: {e!r}")

        try:
            n_flight = _flight.dump_jsonl(os.path.join(path, "flight.jsonl"))
        except Exception as e:
            n_flight = 0
            errors.append(f"flight.jsonl: {e!r}")
        part("engine.json", self.engine.describe_state)
        part("registry.json", _observe.snapshot)
        part("timeline.json", _exporters.flight_trace_dict)

        def decisions():
            import thunder_tpu as tt

            return tt.compile_stats(self.engine.runner.decode_jit) \
                .last_decisions
        part("decisions.json", decisions)
        part("MANIFEST.json", lambda: {
            "engine_id": self.engine.engine_id,
            "cause": repr(cause),
            "cause_type": (type(cause).__name__
                           if isinstance(cause, BaseException) else "str"),
            "created_s": time.time(),
            "step": self.engine._step_count,
            "restarts": self.restarts,
            "health": (self.health.state if self.health is not None
                       else None),
            "budget": self.budget.describe(),
            "flight_records": n_flight,
            "registry_enabled": _observe.is_enabled(),
            "errors": errors,
            "files": ["flight.jsonl", "engine.json", "registry.json",
                      "timeline.json", "decisions.json"],
        })
        self._obs.inc("serving.postmortems")
        self._obs.event("serving_postmortem", path=path, cause=repr(cause))
        return path

    def _restart(self, cause: BaseException) -> None:
        """The engine-level fallback rung: charge the sliding-window
        budget, rebuild pools + binding, re-admit in-flight requests."""
        if not self.budget.record():
            self._obs.event("serving_restart_budget_exhausted",
                           cause=repr(cause), budget=self.budget.describe())
            err = RestartBudgetExceeded(
                f"engine restart budget exhausted "
                f"({self.budget.describe()}); last fault: {cause!r}",
                in_window=self.budget.in_window,
                max_restarts=self.budget.max_restarts,
                engine_id=self.engine.engine_id)
            self.dump_postmortem(err)
            raise err from cause
        t0 = time.perf_counter()
        # hand the fault's typed restart state back to the engine: the
        # rebuild must reproduce the EXACT pool spec the crashed dispatch
        # ran against — geometry, dtype, and the tensor-parallel mesh —
        # not just shapes re-derived from geometry
        recovered = self.engine.rebuild_after_fault(
            getattr(cause, "restart_state", None))
        self.restarts += 1
        self._obs.inc("serving.engine_restarts")
        self._obs.event("serving_engine_restart", cause=repr(cause),
                       recovered=[r.request_id for r in recovered],
                       restart_ms=(time.perf_counter() - t0) * 1e3,
                       budget=self.budget.describe())

"""Supervised serving-engine lifecycle: crash recovery, graceful drain,
and a stall watchdog.

The paper's core discipline — every fast path gets an always-available
fallback rung — extended one level up: the *engine itself* is the fast
path here, and the fallback rung is a supervised restart. PR 7/8 gave
training this story (fault domains, retry budgets, quarantine, the
numerics sentinel); :class:`EngineSupervisor` is the serving counterpart:

- **Crash recovery.** When a dispatch fault consumes the donated page
  pools mid-execution, the engine's retry classifier escalates FATAL and
  the scheduler raises :class:`~thunder_tpu.serving.errors.EngineFault`.
  The supervisor rebuilds the pools and the decode binding
  (:meth:`ServingEngine.rebuild_after_fault`) and re-admits every
  in-flight request by re-prefilling prompt + generated tokens — PR 10's
  recompute-on-resume discipline generalized from *preemption* to *crash*
  recovery, so surviving outputs stay token-identical to a fault-free run.
- **Restart budget.** Each restart charges a
  :class:`~thunder_tpu.runtime.retry.RestartBudget` sliding window; an
  engine failing faster than restarts can honestly mask escalates
  :class:`~thunder_tpu.serving.errors.RestartBudgetExceeded` to the
  caller instead of flapping forever.
- **Graceful drain/shutdown.** :meth:`drain` stops admissions (later
  ``submit()`` raises ``AdmissionRejected``), finishes residents under an
  optional wall-clock bound (expiry sheds the rest with
  ``DeadlineExceeded``), and records the whole episode in the
  ``serving.drain_ms`` histogram.
- **Stall watchdog.** With ``heartbeat_path=`` set, every :meth:`step`
  publishes a heartbeat and an :class:`~thunder_tpu.elastic.Watchdog`
  thread escalates when it goes stale — a dispatch hung inside the device
  never raises, but its heartbeat age climbs
  (``runtime.heartbeat_age_s``) and ``on_stall`` fires instead of the
  engine hanging forever unobserved.

>>> sup = EngineSupervisor(engine, max_restarts=3, restart_window_s=600.0)
>>> req = sup.submit(prompt, max_new_tokens=32, deadline_s=30.0)
>>> sup.drain(deadline_s=120.0)   # stop admissions, finish residents
>>> sup.shutdown()
"""

from __future__ import annotations

import time
from typing import Callable

from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime import retry as _retry
from thunder_tpu.serving.errors import EngineFault, RestartBudgetExceeded
from thunder_tpu.serving.scheduler import Request, ServingEngine


class EngineSupervisor:
    """Wraps a :class:`ServingEngine` with the restart/drain/watchdog
    lifecycle. All request traffic should flow through the supervisor
    (``submit``/``step``/``drain``) so faults recover transparently."""

    def __init__(self, engine: ServingEngine, *,
                 restart_budget: _retry.RestartBudget | None = None,
                 max_restarts: int = 3, restart_window_s: float = 600.0,
                 heartbeat_path: str | None = None,
                 stall_timeout_s: float = 30.0,
                 on_stall: Callable[[float], None] | None = None):
        self.engine = engine
        self.budget = restart_budget or _retry.RestartBudget(
            max_restarts=max_restarts, window_s=restart_window_s)
        self.restarts = 0
        self.on_stall = on_stall
        self.heartbeat = None
        self.watchdog = None
        if heartbeat_path is not None:
            from thunder_tpu.elastic import Heartbeat, Watchdog

            self.heartbeat = Heartbeat(heartbeat_path)
            self.watchdog = Watchdog(heartbeat_path, stall_timeout_s,
                                     escalate=self._escalate_stall).start()

    # -- request traffic ----------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, **kwargs) -> Request:
        """Delegates to the engine (draining engines raise
        ``AdmissionRejected`` there — one admission gate, not two)."""
        return self.engine.submit(prompt, max_new_tokens, **kwargs)

    def step(self) -> bool:
        """One supervised engine iteration: publish the heartbeat, run the
        engine step, and turn an ``EngineFault`` into a budget-charged
        restart instead of a crash. Returns whether progress was made
        (a restart counts — recovery IS progress)."""
        if self.heartbeat is not None:
            self.heartbeat.beat(self.engine._step_count)
        try:
            return self.engine.step()
        except EngineFault as e:
            self._restart(e)
            return True

    def drain(self, *, deadline_s: float | None = None,
              max_steps: int = 1_000_000) -> list[Request]:
        """Graceful drain: stop admissions, then run residents and queued
        requests to completion under ``deadline_s`` (wall clock). On bound
        expiry the remainder is shed with ``DeadlineExceeded``; a
        no-progress step raises ``EngineStallError`` (same contract as
        ``ServingEngine.drain``, but each step here is supervised, so an
        engine fault mid-drain restarts and keeps draining). Records the
        episode in ``serving.drain_ms`` and returns the completed list."""
        eng = self.engine
        eng.stop_admissions()
        t0 = time.perf_counter()
        try:
            for _ in range(max_steps):
                if eng.idle:
                    break
                if deadline_s is not None and \
                        time.perf_counter() - t0 > deadline_s:
                    victims = eng.shed_outstanding(
                        f"drain wall-clock bound ({deadline_s}s) expired")
                    _observe.event("serving_drain_bound_expired",
                                   shed=[r.request_id for r in victims])
                    break
                if not self.step():
                    raise eng._stall_error("no-progress step during drain")
            else:
                if not eng.idle:
                    raise eng._stall_error(
                        f"no completion in {max_steps} drain steps")
        finally:
            _observe.observe_value("serving.drain_ms",
                                   (time.perf_counter() - t0) * 1e3)
        return eng.completed

    def shutdown(self, *, deadline_s: float | None = None) -> list[Request]:
        """Drain (bounded when ``deadline_s`` is given), then stop the
        watchdog thread. Terminal: the engine stays non-admitting."""
        try:
            return self.drain(deadline_s=deadline_s)
        finally:
            self.close()

    def close(self) -> None:
        """Stop the watchdog thread (idempotent). Does not drain."""
        if self.watchdog is not None:
            self.watchdog.stop()

    # -- recovery internals -------------------------------------------------
    def _escalate_stall(self, age_s: float) -> None:
        _observe.event("serving_engine_stalled", age_s=age_s,
                       step=self.engine._step_count)
        if self.on_stall is not None:
            self.on_stall(age_s)

    def _restart(self, cause: BaseException) -> None:
        """The engine-level fallback rung: charge the sliding-window
        budget, rebuild pools + binding, re-admit in-flight requests."""
        if not self.budget.record():
            _observe.event("serving_restart_budget_exhausted",
                           cause=repr(cause), budget=self.budget.describe())
            raise RestartBudgetExceeded(
                f"engine restart budget exhausted "
                f"({self.budget.describe()}); last fault: {cause!r}",
                in_window=self.budget.in_window,
                max_restarts=self.budget.max_restarts) from cause
        t0 = time.perf_counter()
        recovered = self.engine.rebuild_after_fault()
        self.restarts += 1
        _observe.inc("serving.engine_restarts")
        _observe.event("serving_engine_restart", cause=repr(cause),
                       recovered=[r.request_id for r in recovered],
                       restart_ms=(time.perf_counter() - t0) * 1e3,
                       budget=self.budget.describe())

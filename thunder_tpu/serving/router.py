"""Fleet router: health-aware, cache-affine request placement across N
supervised engines.

The paper's thesis — a trace compiler should dispatch each region to
whichever executor serves it best — recurs one level up at pod scale:
which *engine* should serve this request. :class:`FleetRouter` fronts N
:class:`~thunder_tpu.serving.supervisor.EngineSupervisor`\\ s behind one
``submit()``/``step()`` surface and makes placement a first-class,
observable, cost-scored decision:

- **Routing policies** are pluggable and composable: the router walks its
  policy chain in order — each policy may *narrow* the candidate set
  (:meth:`RoutingPolicy.filter`) and/or *pick* an engine
  (:meth:`RoutingPolicy.pick`); the first pick wins. The default chain is
  :class:`HealthGate` (never route to a DEGRADED/DRAINING/DEAD engine —
  the :mod:`~thunder_tpu.serving.health` state machine's verdicts are the
  gate), :class:`PrefixAffinity` (prefer the engine whose prefix-cache
  trie is warm for this prompt; when the whole fleet is cold, pin the
  prefix to one engine by hashing its
  :func:`~thunder_tpu.serving.prefix_cache.content_key` so the NEXT
  request with the same prefix lands warm), then :class:`LeastLoaded`
  (fewest waiting requests, most free KV pages — the same quantities the
  labeled ``serving.queue_depth`` / ``serving.kv_pages_free`` gauges
  publish). Affinity abstains when honoring it would breach its
  load-imbalance bound, falling back to least-loaded.
- **Every decision is logged**: the engine chosen, the policy that chose
  it, its score inputs, and every alternative rejected (with why) land in
  :attr:`FleetRouter.decisions` and in the always-on flight ring as
  ``serving_route_decision`` events — ``observe.explain()`` renders them
  as the "fleet router" section, alive even with the registry disabled.
- **Failover re-admission**: when an engine exhausts its restart budget
  (:class:`~thunder_tpu.serving.errors.RestartBudgetExceeded` out of a
  supervised step — the health plane's terminal DEAD verdict), the router
  rebuilds the dead engine's pools, extracts every in-flight request, and
  re-admits each on a healthy sibling via the existing recompute-on-
  resume discipline (prompt + generated tokens re-prefill), so surviving
  outputs stay token-identical to an undisturbed run. The DEAD
  transition's cross-engine postmortem bundle embeds the flight ring —
  which names every migrated request in its ``serving_route_migrate``
  events.
- **Drain/rebalance**: :meth:`rebalance` migrates *queued* (not
  resident) requests off engines the health plane reports DRAINING, and
  fleet-edge admission applies the SLO machinery — priorities against a
  fleet-wide bounded queue — *before* picking an engine, so overload
  sheds once at the router instead of ping-ponging per-engine
  rejections.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from thunder_tpu.observe import registry as _observe
from thunder_tpu.serving.errors import (
    AdmissionRejected,
    RestartBudgetExceeded,
)
from thunder_tpu.serving.health import DEAD, DRAINING, HEALTHY, FleetObservatory
from thunder_tpu.serving.prefix_cache import content_key
from thunder_tpu.serving.scheduler import Request


class RoutingPolicy:
    """One link of the router's policy chain. ``filter`` narrows the
    candidate set (gates); ``pick`` chooses an engine or abstains with
    ``None`` (scorers). Both receive the router so they can read engine
    state; both return a notes dict that lands verbatim in the decision
    log — a policy that abstains or rejects must say why."""

    name = "policy"

    def filter(self, router: "FleetRouter", candidates: list[str],
               prompt, priority: int):
        """Return ``(kept, rejected)`` where ``rejected`` maps engine_id
        to the reason it left the candidate set."""
        return candidates, {}

    def pick(self, router: "FleetRouter", candidates: list[str],
             prompt, priority: int):
        """Return ``(engine_id | None, notes)`` — ``None`` abstains and
        the chain continues."""
        return None, {}


class HealthGate(RoutingPolicy):
    """Admit only engines the health plane currently calls HEALTHY — a
    DEGRADED engine is shedding breaches, a DRAINING one refuses
    admissions anyway, and a DEAD one is terminal. Uses the router's
    cached verdicts (refreshed every ``step()``), so gating reads the
    same state machine statusz and postmortems report."""

    name = "health_gate"

    def filter(self, router, candidates, prompt, priority):
        kept, rejected = [], {}
        for eid in candidates:
            state = router.states.get(eid, HEALTHY)
            if state == HEALTHY:
                kept.append(eid)
            else:
                rejected[eid] = state
        return kept, rejected


class PrefixAffinity(RoutingPolicy):
    """Cache-affine placement: prefer the engine whose prefix trie is
    warm for this prompt (most cached prefix tokens, via the same
    ``lookup`` the admission path runs). When every trie is cold, pin the
    prompt's :func:`content_key` digest to one engine so repeats of the
    same prefix concentrate instead of spraying — warm-TTFT is a
    placement outcome, not luck. Abstains (falls back to the next policy)
    when the preferred engine already has ``imbalance_bound`` more
    waiting requests than the least-loaded candidate: affinity is a
    performance preference, not a load-balancing override."""

    name = "prefix_affinity"

    def __init__(self, imbalance_bound: int = 4):
        self.imbalance_bound = int(imbalance_bound)

    def pick(self, router, candidates, prompt, priority):
        cached = [eid for eid in candidates
                  if router.engines[eid].prefix is not None]
        if not cached:
            return None, {"abstain": "no prefix caches in fleet"}
        page_size = router.engines[cached[0]].geom.page_size
        if (len(prompt) - 1) // page_size < 1:
            # shorter than one full page: the trie can never cache it, so
            # neither warmth nor pinning applies — load balance instead
            return None, {"abstain": "no cacheable prefix pages"}
        warm = {}
        for eid in cached:
            trie = router.engines[eid].prefix
            warm[eid] = len(trie.lookup(prompt)) * trie.page_size
        digest = content_key(prompt, page_size=page_size)
        best = max(cached, key=lambda e: warm[e])
        if warm[best] > 0:
            target, basis = best, "warm_hit"
        else:
            target = sorted(cached)[int(digest, 16) % len(cached)]
            basis = "hash_pin"
        loads = {eid: router.load(eid) for eid in candidates}
        notes = {"basis": basis, "warm_tokens": warm, "digest": digest,
                 "load": loads}
        if loads[target] - min(loads.values()) > self.imbalance_bound:
            notes["abstain"] = (
                f"imbalance: {target} load {loads[target]} exceeds "
                f"min {min(loads.values())} by more than "
                f"{self.imbalance_bound}")
            return None, notes
        return target, notes


class LeastLoaded(RoutingPolicy):
    """Terminal fallback: fewest waiting requests (queue depth + resident
    slots), ties broken by most free KV pages — the quantities the
    engine-labeled ``serving.queue_depth`` / ``serving.active_requests`` /
    ``serving.kv_pages_free`` gauges publish, read straight off the
    engines so the decision works with the registry disabled."""

    name = "least_loaded"

    def pick(self, router, candidates, prompt, priority):
        scores = {eid: {"load": router.load(eid),
                        "kv_pages_free": router.engines[eid].cache.pages_free}
                  for eid in candidates}
        target = min(candidates,
                     key=lambda e: (scores[e]["load"],
                                    -scores[e]["kv_pages_free"], e))
        return target, {"scores": scores}


class RandomPlacement(RoutingPolicy):
    """Seeded uniform-random placement — the control arm benchmarks
    compare affinity routing against. Never use it in a real chain."""

    name = "random"

    def __init__(self, seed: int = 0):
        self._rng = np.random.RandomState(seed)

    def pick(self, router, candidates, prompt, priority):
        return candidates[int(self._rng.randint(len(candidates)))], {}


class FleetRouter:
    """One ``submit()``/``step()`` surface over N supervised engines.

    ``supervisors`` is the fleet; a shared
    :class:`~thunder_tpu.serving.health.FleetObservatory` is created (or
    passed via ``observatory=``) so routing, statusz, and postmortems all
    read the same health verdicts. ``max_queue`` bounds the TOTAL queued
    requests across the fleet at the router edge: overflow sheds the
    fleet-wide lowest-priority queued request (or rejects the newcomer if
    nothing queued is lower), once, before any engine is picked.
    """

    def __init__(self, supervisors, *, policies=None,
                 observatory: FleetObservatory | None = None,
                 max_queue: int | None = None, decision_log: int = 256):
        sups = list(supervisors)
        if not sups:
            raise ValueError("FleetRouter needs at least one supervisor")
        self.fleet = observatory if observatory is not None \
            else FleetObservatory()
        for sup in sups:
            if sup.engine.engine_id not in self.fleet.supervisors:
                self.fleet.add(sup)
        self.sups = {s.engine.engine_id: s for s in sups}
        self.engines = {eid: s.engine for eid, s in self.sups.items()}
        self.policies = list(policies) if policies is not None else \
            [HealthGate(), PrefixAffinity(), LeastLoaded()]
        self.max_queue = max_queue
        self.decisions: deque = deque(maxlen=decision_log)
        self._decision_seq = 0
        self.states = self.fleet.check()

    # -- state reads --------------------------------------------------------
    def load(self, engine_id: str) -> int:
        """Waiting requests on one engine: queued + resident."""
        eng = self.engines[engine_id]
        return len(eng.queue) + eng.active_requests

    @property
    def idle(self) -> bool:
        return all(e.idle for e in self.engines.values())

    @property
    def completed(self) -> list[Request]:
        """Completion-ordered union of every engine's completed list."""
        done = [r for e in self.engines.values() for r in e.completed]
        return sorted(done, key=lambda r: r.finished_s or 0.0)

    def assert_quiescent(self) -> None:
        for eng in self.engines.values():
            eng.assert_quiescent()

    # -- placement ----------------------------------------------------------
    def _route(self, prompt, priority: int, exclude=()):
        """Walk the policy chain. Returns ``(engine_id | None, record)``
        — ``None`` means no candidate survived (the record still says
        which policy rejected whom)."""
        candidates = sorted(eid for eid in self.sups if eid not in exclude)
        record = {"rejected": {eid: "excluded" for eid in exclude
                               if eid in self.sups},
                  "policies": []}
        for policy in self.policies:
            candidates, rejected = policy.filter(
                self, candidates, prompt, priority)
            record["rejected"].update(rejected)
            if not candidates:
                record["policies"].append({"policy": policy.name,
                                           "exhausted": True})
                return None, record
            choice, notes = policy.pick(self, candidates, prompt, priority)
            record["policies"].append(
                {"policy": policy.name, **notes})
            if choice is not None:
                record["engine"] = choice
                record["policy"] = policy.name
                record["basis"] = notes.get("basis", policy.name)
                record["alternatives"] = [e for e in candidates
                                          if e != choice]
                return choice, record
        # every policy abstained (a gate-only chain): first survivor wins
        record["engine"] = candidates[0]
        record["policy"] = "first_routable"
        record["basis"] = "first_routable"
        record["alternatives"] = candidates[1:]
        return candidates[0], record

    def _log_decision(self, kind: str, record: dict, request_id=None,
                      **extra) -> dict:
        self._decision_seq += 1
        entry = {"seq": self._decision_seq, "kind": kind,
                 "request": request_id, **record, **extra}
        self.decisions.append(entry)
        return entry

    def _shed_for_capacity(self, priority: int) -> None:
        """Fleet-edge bounded queue: applied BEFORE any engine is picked.
        Raises (typed, engine_id=None — the rejection happened above any
        single engine) when the newcomer loses; otherwise sheds the
        fleet-wide lowest-priority queued request in place."""
        if self.max_queue is None:
            return
        queued = [(r, eid) for eid, eng in self.engines.items()
                  for r in eng.queue]
        if len(queued) < self.max_queue:
            return
        victim, victim_eid = min(
            queued, key=lambda rq: (rq[0].priority, -rq[0].request_id)) \
            if queued else (None, None)
        _observe.inc("serving.router_rejections")
        if victim is None or victim.priority >= priority:
            _observe.event("serving_route_reject", priority=priority,
                           fleet_queued=len(queued),
                           max_queue=self.max_queue)
            self._log_decision("reject", {"fleet_queued": len(queued),
                                          "max_queue": self.max_queue,
                                          "priority": priority})
            raise AdmissionRejected(
                f"fleet admission queue full ({self.max_queue}) and every "
                f"queued request has priority >= {priority}",
                engine_id=None)
        _observe.event("serving_route_reject", request=victim.request_id,
                       engine=victim_eid, priority=victim.priority,
                       shed_for_priority=priority,
                       fleet_queued=len(queued))
        self._log_decision("reject", {"engine": victim_eid,
                                      "shed_for_priority": priority},
                           request_id=victim.request_id)
        self.engines[victim_eid]._shed(victim, AdmissionRejected(
            f"request {victim.request_id} (priority {victim.priority}) "
            f"shed from the fleet admission queue for a higher-priority "
            f"arrival", request_id=victim.request_id,
            engine_id=victim_eid))

    def submit(self, prompt, max_new_tokens: int, *, priority: int = 0,
               **kwargs) -> Request:
        """Route one request: fleet-edge SLO admission first (bounded
        queue + priorities — overload sheds HERE, once), then the policy
        chain picks an engine and the request enters that engine's
        ordinary admission path (deadline enforcement included). Raises
        ``AdmissionRejected(engine_id=None)`` when no routable engine
        exists."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._shed_for_capacity(priority)
        eid, record = self._route(prompt, priority)
        if eid is None:
            _observe.inc("serving.router_rejections")
            _observe.event("serving_route_reject", priority=priority,
                           rejected=record["rejected"])
            self._log_decision("reject", record)
            raise AdmissionRejected(
                f"no routable engine: {record['rejected']}", engine_id=None)
        req = self.sups[eid].submit(prompt, max_new_tokens,
                                    priority=priority, **kwargs)
        self._log_decision("route", record, request_id=req.request_id)
        _observe.inc("serving.router_decisions")
        if record["policy"] == "prefix_affinity" \
                and record["basis"] == "warm_hit":
            _observe.inc("serving.router_affinity_hits")
        _observe.event("serving_route_decision", request=req.request_id,
                       engine=eid, policy=record["policy"],
                       basis=record["basis"],
                       alternatives=record["alternatives"],
                       rejected=record["rejected"])
        return req

    # -- fleet stepping -----------------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: step every non-DEAD engine; an engine
        whose restart budget is exhausted mid-step fails over (its
        in-flight requests migrate to healthy siblings) instead of
        crashing the fleet; finish with one health sweep so routing's
        verdicts are at most a step stale."""
        worked = False
        for eid in sorted(self.sups):
            if self.states.get(eid) == DEAD:
                continue
            try:
                worked = self.sups[eid].step() or worked
            except RestartBudgetExceeded as e:
                self._failover(eid, e)
                worked = True
        self.states = self.fleet.check()
        return worked

    def drain(self, max_steps: int = 1_000_000) -> list[Request]:
        """Step the fleet until every engine is idle. Returns completed
        requests fleet-wide in completion order."""
        for _ in range(max_steps):
            if self.idle:
                break
            self.step()
        return self.completed

    def _failover(self, engine_id: str, cause: RestartBudgetExceeded):
        """Failover re-admission: the refused restart left ``engine_id``
        with consumed pools and stranded residents. Rebuild its pools
        (``rebuild_after_fault`` — the same recompute-on-resume reset the
        supervisor's restart rung uses, so token identity is inherited,
        and the dead engine ends quiescent), then re-route every
        in-flight request to a healthy sibling. The health sweep that
        follows records the DEAD transition and auto-dumps the
        cross-engine postmortem — whose flight ring names every migrated
        request. Raises ``cause`` when no sibling is routable (the
        failure must escalate, not strand requests silently)."""
        eng = self.engines[engine_id]
        eng.rebuild_after_fault()      # residents -> queue, fresh pools
        victims = list(eng.queue)
        migrated = []
        for req in victims:
            target, record = self._route(req.work_prompt, req.priority,
                                         exclude=(engine_id,))
            if target is None:
                break
            eng.queue.remove(req)
            self.engines[target].queue.append(req)
            migrated.append(req)
            self._log_decision("migrate", record,
                               request_id=req.request_id,
                               from_engine=engine_id)
            _observe.inc("serving.router_migrated_requests")
            _observe.event("serving_route_migrate", request=req.request_id,
                           from_engine=engine_id, engine=target,
                           generated=len(req.generated),
                           restarts=req.restarts, cause=repr(cause))
        self.states = self.fleet.check()   # DEAD transition + postmortem
        if len(migrated) < len(victims):
            raise cause

    # -- drain / rebalance --------------------------------------------------
    def rebalance(self) -> list[Request]:
        """Migrate queued (not resident) requests off every DRAINING
        engine onto routable siblings — residents keep their KV and
        finish where they are; queued requests have no device state, so
        moving them is free. Requests with no routable target stay put
        (the drain's own deadline machinery decides their fate)."""
        self.states = self.fleet.check()
        moved = []
        for eid in sorted(self.sups):
            if self.states.get(eid) != DRAINING:
                continue
            eng = self.engines[eid]
            for req in list(eng.queue):
                target, record = self._route(req.work_prompt, req.priority,
                                             exclude=(eid,))
                if target is None:
                    break
                eng.queue.remove(req)
                self.engines[target].queue.append(req)
                moved.append(req)
                self._log_decision("rebalance", record,
                                   request_id=req.request_id,
                                   from_engine=eid)
                _observe.inc("serving.router_rebalanced_requests")
                _observe.event("serving_route_rebalance",
                               request=req.request_id, from_engine=eid,
                               engine=target, priority=req.priority)
        return moved

    def describe(self) -> dict:
        """Router state for statusz/postmortem embedding: health verdicts,
        per-engine load, and the decision log tail."""
        return {
            "engines": {eid: {"state": self.states.get(eid),
                              "load": self.load(eid),
                              "kv_pages_free": eng.cache.pages_free}
                        for eid, eng in self.engines.items()},
            "max_queue": self.max_queue,
            "decisions": list(self.decisions)[-16:],
        }

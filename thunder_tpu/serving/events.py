"""The serving event vocabulary.

Every ``observe.event()`` the serving layer emits uses a kind from
``EVENT_KINDS`` — the names are an ops contract (postmortem triage
scripts, dashboards, and the flight-recorder timeline all key on them),
so the vocabulary is pinned here and enforced in BOTH directions by
``tests/test_docs.py::test_serving_event_kinds_documented``: a kind
emitted in code but missing from this set (or from the docs table) fails
tier-1, and a kind registered here (or documented) that no code emits
fails too — the same discipline as the block planner's
``BLOCK_DECISION_KINDS``.

Lifecycle kinds trace one request end to end (always recorded in the
flight ring, registry on or off)::

    serving_submitted -> serving_admitted -> serving_prefill_chunk(s)
      -> serving_first_token -> serving_complete
    (with serving_preempt / serving_engine_restart detours re-entering at
     serving_admitted, and serving_shed as the error terminal)

The remaining kinds describe the engine lifecycle: dispatch/admission
faults, decode re-binds, supervisor restarts and their budget, drain
bounds, stall escalation, SLO collapse, and postmortem bundle dumps.
"""

from __future__ import annotations

EVENT_KINDS = frozenset({
    # request lifecycle
    "serving_submitted",            # request entered the admission queue
    "serving_admitted",             # request took a decode slot (also resume)
    "serving_prefill_chunk",        # one prompt chunk written to KV pages
    "serving_first_token",          # first sampled token (TTFT edge)
    "serving_complete",             # terminal: finished (EOS / max tokens)
    "serving_shed",                 # terminal: removed with a typed error
    "serving_preempt",              # evicted to the queue (page pressure)
    "serving_prefix_hit",           # admission probe matched cached prompt
    #                                 pages; prefill starts past them
    "serving_fork",                 # best-of clone forked a primary's block
    #                                 table copy-on-write into a slot
    "serving_cache_evict",          # allocator reclaimed parked prefix-cache
    #                                 pages (trie subtree dropped)
    # engine lifecycle / supervision
    "serving_mesh",                 # tensor-parallel mesh committed (build /
    #                                 rebuild): mesh_shape + tp_degree
    "serving_decode_bind",          # decode program (re)bound; launch shape
    "serving_decode_rebind",        # re-bind forced by a quarantine-epoch move
    "serving_admission_fault",      # contained admission-domain fault
    "serving_engine_restart",       # supervisor crash recovery
    "serving_engine_stalled",       # watchdog stall escalation
    "serving_drain_bound_expired",  # drain wall-clock bound shed the rest
    "serving_restart_budget_exhausted",  # restart rung refused; escalating
    "serving_slo_collapse",         # rolling SLO attainment fell below floor
    "serving_postmortem",           # black-box bundle written to disk
    # fleet observatory (health.py)
    "serving_health_transition",    # EngineHealth state moved (from/to +
    #                                 the breach reasons that drove it)
    "serving_fleet_postmortem",     # cross-engine bundle written: names the
    #                                 faulting engine, captures siblings
    # fleet router (router.py)
    "serving_route_decision",       # placement chosen: engine, policy,
    #                                 basis, alternatives rejected
    "serving_route_migrate",        # failover re-admission: in-flight
    #                                 request moved off a dead engine
    "serving_route_rebalance",      # queued request moved off a DRAINING
    #                                 engine
    "serving_route_reject",         # fleet-edge admission shed: no routable
    #                                 engine, or the bounded queue overflowed
})

"""Engine health scoring and the fleet observatory.

ROADMAP item 1(c) — a router spreading traffic over N supervised engines —
needs two things before any routing policy can exist: a per-engine health
verdict it can trust, and fleet-level aggregation that doesn't require the
engines to share anything but a process (or, via statusz files, not even
that). This module is both.

:class:`EngineHealth` rolls the signals the serving layer already
maintains — SLO attainment since the last transition, restart-budget
headroom (:meth:`~thunder_tpu.runtime.retry.RestartBudget.describe`),
queue depth vs ``max_queue``, KV page pressure, and the decode-rebind
rate — into a typed four-state machine::

    HEALTHY --any breach--> DEGRADED --recover_checks clean--> HEALTHY
       |                        |
       +--admissions stopped----+--> DRAINING   (terminal-ish: un-drains
       |                        |                never happen today)
       +--restart budget spent--+--> DEAD       (terminal)

with hysteresis: degradation is immediate (a router should stop sending
traffic NOW), recovery needs ``recover_checks`` consecutive clean checks
(flapping between verdicts is worse for a router than a pessimistic one).
Every transition emits a ``serving_health_transition`` event under the
engine's label and moves the per-engine ``serving.health_state`` gauge.

:class:`FleetObservatory` aggregates N supervisors: ``check()`` runs every
health machine (auto-dumping a fleet postmortem on a degrading
transition), ``slo_attainment()`` is the fleet-wide ratio, ``explain()``
renders the merged fleet section, and :meth:`dump_fleet_postmortem`
writes a bundle that names the faulting engine while capturing every
sibling's state — cross-engine correlation is the whole point: "e1 died
while e0's queue spiked" is a fleet fact no single engine's ring shows.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

from thunder_tpu.observe import registry as _observe

HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
DRAINING = "DRAINING"
DEAD = "DEAD"

# the typed health vocabulary — pinned here and enforced against the docs
# table in BOTH directions by tests/test_docs.py (the BLOCK_DECISION_KINDS
# discipline): a state added in code but undocumented fails tier-1, and a
# documented state nothing can reach fails too
HEALTH_STATES = (HEALTHY, DEGRADED, DRAINING, DEAD)

# numeric codes for the serving.health_state gauge (Prometheus/Perfetto
# render numbers; the event carries the names)
HEALTH_STATE_CODE = {s: i for i, s in enumerate(HEALTH_STATES)}


@dataclass(frozen=True)
class HealthPolicy:
    """Thresholds for the degradation signals (all judged per check).

    ``slo_floor`` with fewer than ``min_slo_samples`` terminals since the
    last transition is not judged (cold engines are healthy, not lucky).
    ``queue_fill_degraded`` only applies to bounded queues (``max_queue``
    set). ``recover_checks`` is the hysteresis width: consecutive clean
    checks needed before DEGRADED flips back to HEALTHY."""

    slo_floor: float = 0.8
    min_slo_samples: int = 4
    queue_fill_degraded: float = 0.9
    page_free_degraded: float = 0.05
    restart_headroom_min: int = 1
    recover_checks: int = 2


class EngineHealth:
    """The per-engine health state machine over one :class:`EngineSupervisor`.

    ``check()`` evaluates the signals and returns the (possibly new) state;
    ``describe()`` returns the signals WITH the verdict, for statusz
    payloads and postmortems. Restart detection is edge-triggered (a new
    restart since the previous check is a breach even though the engine is
    up again) — that is what makes a crash + token-identical rebuild read
    HEALTHY → DEGRADED → (clean checks) → HEALTHY instead of staying
    green throughout."""

    def __init__(self, supervisor, policy: HealthPolicy | None = None):
        self.sup = supervisor
        self.engine = supervisor.engine
        self.policy = policy or HealthPolicy()
        self.state = HEALTHY
        self.transitions: list[dict] = []
        self._clean = 0
        self._last_restarts = supervisor.restarts
        self._last_rebinds = self.engine.decode_rebinds
        # SLO window base: judged since the last transition (or attach)
        self._slo_base = (self.engine._slo_attained, self.engine._slo_total,
                          self.engine._slo_resets)
        self._publish()

    # -- signals ------------------------------------------------------------
    def signals(self) -> dict:
        """Evaluate every degradation signal; ``breaches`` lists the ones
        that fired (reason strings — they go into the transition event)."""
        eng, sup, pol = self.engine, self.sup, self.policy
        breaches: list[str] = []

        new_restarts = sup.restarts - self._last_restarts
        if new_restarts > 0:
            breaches.append(f"engine_restart(+{new_restarts})")

        new_rebinds = eng.decode_rebinds - self._last_rebinds
        if new_rebinds > 0:
            breaches.append(f"decode_rebind(+{new_rebinds})")

        base_a, base_t, base_gen = self._slo_base
        if eng._slo_resets != base_gen:
            self._slo_base = (0, 0, eng._slo_resets)
            base_a, base_t = 0, 0
        total = eng._slo_total - base_t
        slo = (eng._slo_attained - base_a) / total if total else None
        if (total >= max(pol.min_slo_samples, 1) and slo is not None
                and slo < pol.slo_floor):
            breaches.append(f"slo_attainment({slo:.3f}<{pol.slo_floor:g})")

        queue_fill = (len(eng.queue) / eng.max_queue
                      if eng.max_queue else None)
        if queue_fill is not None and queue_fill >= pol.queue_fill_degraded:
            breaches.append(f"queue_fill({queue_fill:.2f})")

        page_free = (eng.cache.pages_free / eng.cache.pages_total
                     if eng.cache.pages_total else 1.0)
        if page_free < pol.page_free_degraded:
            breaches.append(f"kv_page_pressure(free={page_free:.3f})")

        headroom = sup.budget.max_restarts - sup.budget.in_window
        if headroom < pol.restart_headroom_min:
            breaches.append(f"restart_headroom({headroom})")

        return {
            "restarts": sup.restarts,
            "new_restarts": new_restarts,
            "decode_rebinds": eng.decode_rebinds,
            "new_rebinds": new_rebinds,
            "slo_attainment": None if slo is None else round(slo, 4),
            "slo_samples": total,
            "queue_depth": len(eng.queue),
            "queue_fill": queue_fill,
            "page_free_frac": round(page_free, 4),
            "restart_headroom": headroom,
            "budget": sup.budget.describe(),
            "admitting": eng.admitting,
            "breaches": breaches,
        }

    # -- the state machine --------------------------------------------------
    def check(self) -> str:
        """One health evaluation. Degradation is immediate; recovery needs
        ``recover_checks`` consecutive clean checks. DRAINING tracks the
        admission gate; DEAD (restart budget spent) is terminal."""
        sig = self.signals()
        self._last_restarts = self.sup.restarts
        self._last_rebinds = self.engine.decode_rebinds
        if self.state == DEAD:
            return self.state

        # DEAD only once the budget actually REFUSED a restart (in_window
        # can only exceed max after a refused record()) — zero headroom
        # with the engine still up is a DEGRADED breach, not death
        if self.sup.budget.in_window > self.sup.budget.max_restarts:
            self._transition(DEAD, sig)
            return self.state
        if not self.engine.admitting:
            if self.state != DRAINING:
                self._transition(DRAINING, sig)
            return self.state
        if self.state == DRAINING:
            # admissions resumed (engine rebuilt/repointed under us)
            self._transition(HEALTHY, sig)
            return self.state

        if sig["breaches"]:
            self._clean = 0
            if self.state != DEGRADED:
                self._transition(DEGRADED, sig)
        elif self.state == DEGRADED:
            self._clean += 1
            if self._clean >= self.policy.recover_checks:
                self._transition(HEALTHY, sig)
        return self.state

    def _transition(self, to: str, sig: dict) -> None:
        frm, self.state = self.state, to
        self._clean = 0
        # recovery judges a FRESH SLO window, not the misses that degraded us
        self._slo_base = (self.engine._slo_attained, self.engine._slo_total,
                          self.engine._slo_resets)
        rec = {"from": frm, "to": to, "step": self.engine._step_count,
               "breaches": list(sig.get("breaches", ()))}
        self.transitions.append(rec)
        obs = self.engine.obs
        obs.inc("serving.health_transitions")
        obs.event("serving_health_transition", engine=self.engine.engine_id,
                  **rec)
        self._publish()

    def _publish(self) -> None:
        self.engine.obs.set_gauge("serving.health_state",
                                  HEALTH_STATE_CODE[self.state])

    def describe(self) -> dict:
        return {"engine_id": self.engine.engine_id, "state": self.state,
                "signals": self.signals(),
                "transitions": list(self.transitions)}


class FleetObservatory:
    """Aggregates N supervised engines into one health/telemetry plane.

    ``add(sup)`` attaches an :class:`EngineHealth` (also exposed as
    ``sup.health`` so statusz payloads carry the verdict); ``check()``
    runs every machine and publishes the fleet gauges; ``explain()`` is
    the merged fleet section. With ``postmortem_dir=`` set, a transition
    INTO ``DEGRADED``/``DEAD`` auto-dumps a fleet postmortem bundle
    naming the faulting engine next to every sibling's state."""

    def __init__(self, *, policy: HealthPolicy | None = None,
                 postmortem_dir: str | None = None):
        self.policy = policy or HealthPolicy()
        self.postmortem_dir = postmortem_dir
        self.supervisors: dict[str, object] = {}
        self.health: dict[str, EngineHealth] = {}

    def add(self, supervisor, policy: HealthPolicy | None = None) -> EngineHealth:
        eid = supervisor.engine.engine_id
        if eid in self.supervisors:
            raise ValueError(f"engine {eid!r} already under observation")
        h = EngineHealth(supervisor, policy or self.policy)
        supervisor.health = h
        self.supervisors[eid] = supervisor
        self.health[eid] = h
        _observe.set_gauge("serving.fleet_engines", len(self.health))
        return h

    def check(self) -> dict[str, str]:
        """Run every engine's health check; returns ``{engine_id: state}``.
        Publishes fleet-wide gauges and auto-dumps a fleet postmortem for
        every transition into DEGRADED/DEAD (one bundle per transition,
        not per check — re-checking a degraded fleet is free)."""
        states: dict[str, str] = {}
        for eid, h in self.health.items():
            prev = h.state
            st = h.check()
            states[eid] = st
            if st != prev and st in (DEGRADED, DEAD):
                breaches = (h.transitions[-1].get("breaches", [])
                            if h.transitions else [])
                self.dump_fleet_postmortem(
                    eid, f"{prev}->{st}: {', '.join(breaches) or 'unknown'}")
        _observe.set_gauge("serving.fleet_engines", len(self.health))
        slo = self.slo_attainment()
        if slo is not None:
            _observe.set_gauge("serving.fleet_slo_attainment", slo)
        return states

    def slo_attainment(self) -> float | None:
        """Fleet-wide SLO attainment: terminals summed over every engine
        (an idle fleet returns None, not 1.0 — no claim without samples)."""
        attained = sum(s.engine._slo_attained
                       for s in self.supervisors.values())
        total = sum(s.engine._slo_total for s in self.supervisors.values())
        return (attained / total) if total else None

    def describe(self) -> dict:
        slo = self.slo_attainment()
        return {
            "engines": {eid: h.describe() for eid, h in self.health.items()},
            "fleet": {
                "engines": len(self.health),
                "states": {eid: h.state for eid, h in self.health.items()},
                "slo_attainment": None if slo is None else round(slo, 4),
            },
        }

    def explain(self) -> str:
        """The merged fleet section — same shape as ``observe.explain``'s
        serving section, one line per engine plus the fleet rollup."""
        lines = ["== serving fleet =="]
        slo = self.slo_attainment()
        lines.append(f"  engines: {len(self.health)}"
                     + (f"   fleet SLO attainment: {slo:.3f}"
                        if slo is not None else ""))
        for eid, h in sorted(self.health.items()):
            sig = h.signals()
            slo_s = ("-" if sig["slo_attainment"] is None
                     else f"{sig['slo_attainment']:.3f}")
            lines.append(
                f"  {eid}: {h.state:9s} queue={sig['queue_depth']} "
                f"pages_free={sig['page_free_frac']:.2f} slo={slo_s} "
                f"restarts={sig['restarts']} [{sig['budget']}]")
            for t in h.transitions[-3:]:
                lines.append(f"    step {t['step']}: {t['from']} -> {t['to']}"
                             + (f" ({', '.join(t['breaches'])})"
                                if t["breaches"] else ""))
        return "\n".join(lines)

    def write_statusz(self, dir_path: str) -> None:
        """One atomic status file per engine, now (cadence-free: the
        per-supervisor ``statusz_dir=`` writers ride step(); this is the
        observatory-driven flush for engines without one)."""
        from thunder_tpu.observe import statusz as _statusz

        for eid, sup in self.supervisors.items():
            _statusz.write_status(_statusz.status_path(dir_path, eid),
                                  {"engine_id": eid, **sup.status_payload()})

    @staticmethod
    def aggregate_statusz(dir_path: str, *,
                          stale_after_s: float | None = None) -> dict:
        """Aggregate a directory of statusz snapshots (cross-process: the
        writers need not share this process, only the filesystem)."""
        from thunder_tpu.observe import statusz as _statusz

        return _statusz.read_dir(dir_path, stale_after_s=stale_after_s)

    def dump_fleet_postmortem(self, engine_id: str, cause) -> str | None:
        """The cross-engine black box: the faulting engine's FULL bundle
        (via its supervisor's ``dump_postmortem`` when it has a
        ``postmortem_dir``, else inline state) plus every sibling's
        ``describe_state``/health — written under this observatory's
        ``postmortem_dir``. Returns the bundle path (None when unset).
        Never raises."""
        if self.postmortem_dir is None:
            return None
        sup = self.supervisors.get(engine_id)
        try:
            base = os.path.join(self.postmortem_dir,
                                f"fleet-postmortem-{engine_id}")
            path, i = base, 1
            while os.path.exists(path):
                path = f"{base}.{i}"
                i += 1
            os.makedirs(path)
        except Exception:
            return None
        from thunder_tpu.observe import exporters as _exporters
        from thunder_tpu.observe import flight as _flight

        errors: list[str] = []

        def part(fname: str, build) -> None:
            try:
                obj = build()
                with open(os.path.join(path, fname), "w") as f:
                    json.dump(_exporters._jsonable(obj), f, default=str)
            except Exception as e:    # partial bundle beats no bundle
                errors.append(f"{fname}: {e!r}")

        try:
            n_flight = _flight.dump_jsonl(os.path.join(path, "flight.jsonl"))
        except Exception as e:
            n_flight = 0
            errors.append(f"flight.jsonl: {e!r}")
        part("fleet.json", self.describe)
        part("siblings.json", lambda: {
            eid: s.engine.describe_state()
            for eid, s in self.supervisors.items()})
        # the shared ring renders once, per-engine process groups and all —
        # THE cross-engine correlation artifact
        part("timeline.json", _exporters.flight_trace_dict)
        part("MANIFEST.json", lambda: {
            "faulting_engine": engine_id,
            "cause": repr(cause),
            "created_s": time.time(),
            "engines": sorted(self.supervisors),
            "states": {eid: h.state for eid, h in self.health.items()},
            "flight_records": n_flight,
            "registry_enabled": _observe.is_enabled(),
            "errors": errors,
            "files": ["flight.jsonl", "fleet.json", "siblings.json",
                      "timeline.json"],
        })
        _observe.inc("serving.fleet_postmortems")
        obs = (sup.engine.obs if sup is not None
               else _observe.labeled(engine=engine_id))
        obs.event("serving_fleet_postmortem", engine=engine_id,
                  path=path, cause=repr(cause))
        return path

"""Paged model runner: the compiled prefill/decode step functions the
serving engine dispatches.

Two traced programs per engine, both shape-stable for the life of the
process:

- ``decode``: ONE batched step over every slot — ``(S, 1)`` tokens against
  the shared page pools, ragged per-slot context lengths handled in-graph
  by ``nn.paged_decode_attention`` (claimed by the Pallas scalar-prefetch
  kernel on TPU; XLA decomposition otherwise), and SAMPLING fused in-graph
  as the epilogue: per-slot sampling-parameter rows + raw threefry keys
  ride in as plain arrays and the program returns sampled TOKEN IDS
  (:func:`~thunder_tpu.serving.sampling.sample_tokens`; greedy is the
  ``temperature == 0`` degenerate case, bit-identical to the host argmax
  it replaced). The scheduler reads tokens, not logits — the prerequisite
  for a fully device-side token loop. Dispatched through ``bind()`` — the
  serving fast path pays zero guard cost per step.
- ``prefill``: one CHUNK of one request's prompt — ``(1, C)`` tokens with
  ``C`` drawn from a ``LengthBucketer`` ladder (multiples of the page
  size), writing the chunk's K/V into the request's pages and attending
  the paged context so far. Ragged prompt lengths compile at most
  ``len(ladder)`` prefill programs, ever. Prefill emits NO logits at all:
  every request's first token comes from a decode REPLAY step (the
  scheduler re-feeds the last prompt token with the write redirected to
  the scratch page), so the lm_head matmul leaves the prefill program
  entirely and the first token is sampled on the exact same program path
  as every later one — which is what makes best-of-N forks and
  recompute-on-resume token-streams line up with the unforked path.

K/V writes address the pools through host-computed flat positions
(``page_id * page_size + offset``) — the host owns the block tables, so the
traced program never does page arithmetic; it just ``dynamic_update_slice``s
at traced scalar positions, which keeps one compiled decode program valid
for every allocation pattern.

Crash-recovery note: both programs take the page pools as DONATED
arguments, so a dispatch that fails mid-execution may leave them consumed
(deleted buffers). The runner's compiled cache entries are keyed on shapes
only and survive a supervisor restart unchanged — rebuilding after an
``EngineFault`` means fresh pools (same shapes) plus a re-``bind_decode``;
no recompilation. The *binding* is engine-owned state (the scheduler drops
and re-creates it), never stored here.
"""

from __future__ import annotations

from thunder_tpu.core import dtypes, prims
from thunder_tpu import ops
from thunder_tpu.ops import nn as tnn
from thunder_tpu.serving.sampling import sample_tokens


def _rope_tables_at(cfg, positions, dtype):
    """Per-request rotary tables: ``positions`` (S,) int32 -> cos/sin
    ``(S, 1, 1, hd/2)``, broadcasting over heads and the single decode row.
    The frequency math lives in ``models.llama._rope_tables`` — ONE owner
    shared with training and prefill, so rope changes can't silently break
    the engine's token-identity with ``generate()``."""
    from thunder_tpu.models.llama import _rope_tables

    cos, sin = _rope_tables(cfg, positions, dtype)     # (S, hd/2)
    shape = (positions.shape[0], 1, 1, cfg.head_dim // 2)
    return ops.reshape(cos, shape), ops.reshape(sin, shape)


def _write_rows(pool, rows, flat_positions):
    """Scatter every slot's K/V row into a flattened page pool in ONE
    scatter op.

    ``pool``: (KV, P*ps, hd); ``rows``: (S, KV, 1, hd); ``flat_positions``:
    (S,) int32 of page*ps+offset. Replace semantics (``prims.scatter``) —
    freed pages hold stale values, so add-style scatters would corrupt.
    Idle slots all target position 0 (the reserved scratch page); duplicate
    indices there are benign (any write wins, nobody reads it). One scatter
    beats S chained dynamic_update_slices: XLA copies the input pool once
    either way, but the chain pays S update kernels.

    The op emission lives in ``ops.nn.decode_row_write`` — ONE owner shared
    with the ``nn.attn_subblock`` decomposition, so the block planner's
    chain matcher and the quarantine fallback always see the exact sequence
    this runner traces."""
    return tnn.decode_row_write(pool, rows, flat_positions)


def _write_pages(pool, rows, page_positions, ps: int):
    """Scatter a prefill chunk's K/V into its pages. ``rows``: (KV, C, hd)
    with C a multiple of ps; ``page_positions``: (C//ps,) int32 flat
    positions (page*ps) — chunks start page-aligned by construction."""
    zero = ops.full((), 0, dtype=dtypes.int32)
    C = rows.shape[1]
    for i in range(C // ps):
        pos = ops.getitem(page_positions, i)
        pool = prims.dynamic_update_slice(pool, ops.narrow(rows, 1, i * ps, ps),
                                          (zero, pos, zero))
    return pool


class PagedLlamaRunner:
    """Builds + owns the compiled paged step functions for one engine."""

    def __init__(self, cfg, geometry, *, n_layers: int | None = None,
                 executors=None, block_fusion=None,
                 launch_budget_per_layer: float | None = None, mesh=None,
                 engine_id: str | None = None):
        import thunder_tpu as tt
        from thunder_tpu.observe import registry as _observe

        self.cfg = cfg
        self.geom = geometry
        self.mesh = mesh  # distributed.gspmd.TensorParallelMesh or None
        # owning engine's label: the runner's gauge/event emissions (decode
        # bind shape) must land in that engine's series, not a shared one
        self.engine_id = engine_id
        self.obs = (_observe.labeled(engine=engine_id)
                    if engine_id is not None else None)
        self.n_layers = n_layers if n_layers is not None else cfg.n_layers
        # decode-launch budget: when set (via census_context below), a
        # decode program dispatching more Pallas launches per layer per
        # token than the budget yields a typed `decode-launch-growth`
        # pessimization finding whenever its census is evaluated
        # (observe.census) — a megakernel falling back to its
        # decomposition becomes a finding, not just a throughput regression
        self.launch_budget_per_layer = launch_budget_per_layer
        # block planner passthrough: unset lets the decode cost model decide
        # (at T==1 serving shapes the launch-amortization objective plans the
        # whole-decode-layer megakernel whenever an executor claims it);
        # True/False force/disable — tests and A/Bs use both
        opts = {} if block_fusion is None else {"block_fusion": block_fusion}
        # tensor-parallel mesh: the step inputs (params, pools) arrive
        # COMMITTED to NamedShardings, so the whole-program jit compiles one
        # SPMD program around them. Pallas launches cannot auto-partition
        # under GSPMD, so the planner caps block fusion ONE rung below the
        # whole-decode-layer megakernel (attention/MLP sub-blocks still
        # plan) — never silently down to per-op XLA
        if mesh is not None and getattr(mesh, "tp", 1) > 1:
            opts["decode_tp_shards"] = int(mesh.tp)
        # one jitted fn each; distinct chunk shapes become distinct cache
        # entries inside the ThunderTPUFunction (bounded by the ladder)
        self.decode_jit = tt.jit(self._decode_fn, executors=executors,
                                 fn_name="serving_decode", donate_argnums=(5,),
                                 **opts)
        self.prefill_jit = tt.jit(self._prefill_fn, executors=executors,
                                  fn_name="serving_prefill", donate_argnums=(5,),
                                  **opts)
        # census context: lets observe.census derive launches-per-layer and
        # re-evaluate the decode-launch-growth finding whenever the decode
        # program's census is taken (explain(), postmortems), not only at
        # the bind-time publication below
        self.decode_jit._stats.census_context = {
            "decode_layers": self.n_layers,
            "decode_launches_per_layer_max": launch_budget_per_layer,
        }
        if mesh is not None and getattr(mesh, "tp", 1) > 1:
            from thunder_tpu.distributed.gspmd import mesh_descriptor

            md = mesh_descriptor(mesh)
            self.decode_jit._stats.census_context.update(md)
            self.prefill_jit._stats.census_context = dict(md)

    # -- traced bodies ------------------------------------------------------
    def _attn_block(self, h, layer, q, block_tables, lengths, pools_kv):
        """Shared attention tail: this step's K/V rows are already written
        into the pools; run paged attention and the residual + MLP."""
        cfg = self.cfg
        B, T = h.shape[0], h.shape[1]
        attn = tnn.paged_decode_attention(q, pools_kv["k"], pools_kv["v"],
                                          block_tables, lengths)
        attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)),
                           (B, T, cfg.n_heads * cfg.head_dim))
        h = ops.add(h, ops.linear(attn, layer["wo"]))
        from thunder_tpu.models.llama import _mlp

        return _mlp(h, layer, cfg)

    def _decode_fn(self, params, tokens, block_tables, lengths, write_pos,
                   pools, temps, top_ks, top_ps, rng):
        """One continuous-batching decode step for every slot.

        tokens (S, 1) int32; block_tables (S, npg) int32; lengths (S,) int32
        context length INCLUDING this token; write_pos (S,) int32 flat pool
        position of this token's K/V row (the scratch position 0 for replay
        rows, whose K/V already exists). Sampling inputs: temps (S,) f32,
        top_ks (S,) int32, top_ps (S,) f32, rng (S, 2) uint32 raw threefry
        keys. Returns (sampled token ids (S,) int32, logits (S, V), pools)
        — the logits output exists for parity tests and future logprob
        surfacing; the scheduler fetches only the token ids."""
        cfg = self.cfg
        g = self.geom
        h = ops.embedding(tokens, params["tok_embedding"])             # (S,1,D)
        cos, sin = _rope_tables_at(cfg, ops.sub(lengths, 1), h.dtype)
        new_pools = []
        flat = (g.kv_heads, g.num_pages * g.page_size, g.head_dim)
        paged = (g.kv_heads, g.num_pages, g.page_size, g.head_dim)
        for layer, kv in zip(params["layers"], pools):
            x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
            q, k, v = self._qkv(x, layer, cos, sin)
            kp = _write_rows(ops.reshape(kv["k"], flat), k, write_pos)
            vp = _write_rows(ops.reshape(kv["v"], flat), v, write_pos)
            kv = {"k": ops.reshape(kp, paged), "v": ops.reshape(vp, paged)}
            new_pools.append(kv)
            h = self._attn_block(h, layer, q, block_tables, lengths, kv)
        h = ops.rms_norm(h, params["norm_f"], eps=cfg.norm_eps)
        logits = ops.squeeze(ops.linear(h, params["lm_head"]), 1)      # (S,V)
        # in-graph sampling epilogue: one more fused tail on the program we
        # already dispatch once per token (greedy == temperature 0)
        toks = sample_tokens(logits, temps, top_ks, top_ps, rng)
        return toks, logits, new_pools

    def _qkv(self, x, layer, cos, sin):
        """RoPE'd q/k/v heads (decode layout: T == x.shape[1])."""
        from thunder_tpu.models.llama import _apply_rope

        cfg = self.cfg
        B, T = x.shape[0], x.shape[1]
        hd = cfg.head_dim
        q = ops.transpose(ops.reshape(ops.linear(x, layer["wq"]),
                                      (B, T, cfg.n_heads, hd)), (0, 2, 1, 3))
        k = ops.transpose(ops.reshape(ops.linear(x, layer["wk"]),
                                      (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        v = ops.transpose(ops.reshape(ops.linear(x, layer["wv"]),
                                      (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        return _apply_rope(q, cos, sin), _apply_rope(k, cos, sin), v

    def _prefill_fn(self, params, tokens, block_tables, lengths, page_writes,
                    pools):
        """One prefill chunk of one request — K/V writes only, no logits.

        tokens (1, C) int32 (C from the bucket ladder, multiple of the page
        size; padded past the prompt tail); block_tables (1, npg); lengths
        (1,) int32 = chunk_start + C (context including the padded chunk);
        page_writes (C//ps,) int32 flat positions of the chunk's pages.
        Returns the updated pools. The first token is sampled by a decode
        REPLAY step after the final chunk lands, so prefill carries no
        lm_head work at all (the old last-row logits slice is gone with
        its host argmax)."""
        cfg = self.cfg
        g = self.geom
        C = tokens.shape[1]
        from thunder_tpu.models.llama import _project_qkv, _rope_cos_sin

        h = ops.embedding(tokens, params["tok_embedding"])             # (1,C,D)
        pos0 = ops.sub(ops.getitem(lengths, 0), C)
        cos, sin = _rope_cos_sin(cfg, C, h.dtype, pos_offset=pos0)
        new_pools = []
        flat = (g.kv_heads, g.num_pages * g.page_size, g.head_dim)
        paged = (g.kv_heads, g.num_pages, g.page_size, g.head_dim)
        for layer, kv in zip(params["layers"], pools):
            x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
            q, k, v = _project_qkv(x, layer, cfg, cos, sin)
            kp = _write_pages(ops.reshape(kv["k"], flat), ops.squeeze(k, 0),
                              page_writes, g.page_size)
            vp = _write_pages(ops.reshape(kv["v"], flat), ops.squeeze(v, 0),
                              page_writes, g.page_size)
            kv = {"k": ops.reshape(kp, paged), "v": ops.reshape(vp, paged)}
            new_pools.append(kv)
            h = self._attn_block(h, layer, q, block_tables, lengths, kv)
        return new_pools

    # -- dispatch -----------------------------------------------------------
    def bind_decode(self, *args):
        """Compile the decode step for these inputs and bind it (zero-guard
        dispatch). The scheduler owns the bound callable and re-binds when
        the quarantine epoch moves (a containment event recompiled under a
        new cache entry; the stale binding would re-contain every call).
        Each (re)bind republishes the decode program's fusion shape to the
        observe registry, so a fallback to the unfused decode layer is
        visible as a launch-count move rather than only as a throughput
        regression."""
        bound = self.decode_jit.bind(*args)
        self._publish_decode_fusion_shape()
        return bound

    def _publish_decode_fusion_shape(self) -> None:
        """Gauges describing the compiled decode step's per-token launch
        shape, fed from the SAME census walk the per-compile observe
        surface uses (``observe.census.trace_census`` — one owner, so the
        serving gauges and ``CompileStats.last_census`` can never disagree):
        how many Pallas launches one decode step dispatches, and how many
        of them are whole-decode-layer megakernels. ``bench_serve.py``
        stamps both; the fusion-shape acceptance test reads
        launches-per-layer from them."""
        import thunder_tpu as tt
        from thunder_tpu.observe import census as _census
        from thunder_tpu.observe import registry as _observe

        try:
            trc = tt.last_execution_trace(self.decode_jit)
        except Exception:
            return
        if trc is None:
            return
        tc = _census.trace_census(trc)
        launches = tc["pallas_launches"]
        layers = tc["decode_layer_fusions"]
        rec = self.obs if self.obs is not None else _observe
        rec.set_gauge("serving.decode_pallas_launches", launches)
        rec.set_gauge("serving.decode_layer_fusions", layers)
        # launch-budget enforcement lives in the census (the census_context
        # stashed at construction): the decode-launch-growth finding is
        # derived — ONCE — whenever the decode program's census is
        # evaluated (explain, postmortems, budget tests), while the
        # serving_decode_bind event below already lands the launch shape
        # in the flight ring at bind time. Recording the finding here too
        # would double-count compile.pessimizations for one condition.
        # lifecycle edge for the flight ring: WHICH program shape is now
        # serving (a postmortem wants to know if the megakernel or a
        # fallback rung was bound when the fault hit)
        rec.event("serving_decode_bind", launches=launches,
                  decode_layer_fusions=layers)

"""Block-allocated paged KV cache for the serving engine.

One shared pool of fixed-size pages per layer (``(kv_heads, num_pages,
page_size, head_dim)`` K and V arrays) plus a host-side free list and
per-request block tables — the vLLM PagedAttention memory model, TPU-first:
requests at wildly different sequence lengths share one device allocation,
so the compiled decode step has ONE shape regardless of who is resident
(no per-request recompiles, no per-request max_len buffers).

Page 0 is reserved as the scratch page: it is never allocated, inactive
decode slots write their (discarded) K/V there, and unallocated block-table
entries point at it — every table entry is always a valid pool index, which
is what lets the Pallas kernel's scalar-prefetch index map run unguarded.

Pages are REFCOUNTED (copy-on-write substrate): ``alloc`` hands out pages
at refcount 1, ``retain`` lets a second block table share a page, and
``free`` only returns a page to the free list when its last reference
drops. :meth:`fork` builds a forked block table that shares every full
page of a context and copies only the partial tail page — the page the
fork will keep appending into — which is what makes best-of-N share ONE
prefill across N decode slots, and draft rollback a refcount decrement.
A page can additionally be REGISTERED by the cross-request prefix cache
(:mod:`~thunder_tpu.serving.prefix_cache`): a registered page whose
refcount reaches zero parks in the *cached* set (evictable, its K/V
preserved for future prefix hits) instead of the free list, and
``alloc`` reclaims cached pages through the registered ``evict_cb``
before ever raising ``OutOfPages`` — cached prefixes can never starve
live traffic.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass


@dataclass
class PageGeometry:
    """Static pool geometry; everything the compiled step's shapes depend on."""

    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int       # tokens per page
    num_pages: int       # pool pages per layer, INCLUDING the reserved page 0
    pages_per_request: int  # block-table width (max context / page_size)

    @property
    def max_context(self) -> int:
        return self.pages_per_request * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` of context."""
        return -(-n_tokens // self.page_size)


class PagedKVCache:
    """Device page pools + host free list + per-page refcounts.

    ``pools`` is a list (per layer) of ``{"k": array, "v": array}`` with
    shape ``(kv_heads, num_pages, page_size, head_dim)``. The arrays are
    functional: the engine passes them into the compiled step (donated) and
    stores the returned updated pools back via :meth:`update_pools`.
    """

    def __init__(self, geometry: PageGeometry, dtype, *, sharding=None):
        import jax.numpy as jnp

        g = geometry
        if g.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if sharding is not None and getattr(sharding, "tp", 1) > 1:
            if g.kv_heads % sharding.tp != 0:
                from thunder_tpu.serving.errors import ShardingGeometryError

                raise ShardingGeometryError(
                    f"kv_heads={g.kv_heads} not divisible by mesh axis "
                    f"'{sharding.axis}' size {sharding.tp}: the paged pool "
                    "is sharded by kv-head, so each shard must own a whole "
                    "number of heads", kv_heads=g.kv_heads, tp=sharding.tp)
        self.geometry = g
        self.dtype = dtype
        # sharding: a distributed.gspmd.TensorParallelMesh (or None). The
        # pool keeps its GLOBAL logical shape — GSPMD splits the kv-head dim
        # across the mesh, so per-shard geometry is (kv_heads/tp, ...) while
        # block tables and the free list stay global (the page axis is whole
        # on every shard).
        self.sharding = sharding if (sharding is not None
                                     and getattr(sharding, "tp", 1) > 1) else None
        shape = (g.kv_heads, g.num_pages, g.page_size, g.head_dim)
        self.pools = [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                      for _ in range(g.n_layers)]
        if self.sharding is not None:
            from thunder_tpu.distributed.gspmd import shard_kv_pools

            self.pools = shard_kv_pools(self.pools, self.sharding)
        # LIFO free list: recently-freed pages are re-served first (their
        # pool region is likeliest still warm in any cache hierarchy); the
        # mirror set keeps free()'s double-free check O(1) per page (a list
        # scan is O(pool) — quadratic on the completion/eviction hot path)
        self._free: list[int] = list(range(g.num_pages - 1, 0, -1))
        self._free_set: set[int] = set(self._free)
        self._min_free = len(self._free)  # high-water tracking (peak usage)
        # copy-on-write substrate: per-page reference counts (0 == free or
        # cached), the prefix cache's registration set, and the parked
        # rc-0 registered pages in eviction (insertion) order
        self._rc: list[int] = [0] * g.num_pages
        self._registered: set[int] = set()
        self._cached: dict[int, None] = {}   # ordered: oldest parked first
        self.evict_cb = None        # page -> list[int]: prefix-cache hook
        self.cow_copies = 0         # tail-page copies made by fork()
        self.pages_allocated = 0    # lifetime alloc count (page amplification)

    # -- allocation ---------------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_total(self) -> int:
        """Allocatable pages (the reserved scratch page doesn't count)."""
        return self.geometry.num_pages - 1

    @property
    def cached_pages(self) -> int:
        """Pages parked by the prefix cache: refcount 0, K/V preserved,
        reclaimable by :meth:`alloc` under pressure."""
        return len(self._cached)

    @property
    def peak_pages_used(self) -> int:
        return self.pages_total - self._min_free

    def utilization(self) -> float:
        return 1.0 - self.pages_free / self.pages_total

    def reset_peak(self) -> None:
        """Restart high-water tracking (benchmarks: exclude warmup)."""
        self._min_free = len(self._free)

    def refcount(self, page: int) -> int:
        return self._rc[page]

    def can_alloc(self, n: int) -> bool:
        # cached pages count: alloc() reclaims them before back-pressuring
        return n <= len(self._free) + len(self._cached)

    def alloc(self, n: int) -> list[int]:
        """Pop ``n`` pages off the free list, reclaiming parked prefix-cache
        pages (oldest first, via ``evict_cb``) when the list runs short.
        Raises ``OutOfPages`` when free + cached can't satisfy the request —
        the scheduler turns that into admission back-pressure or preemption,
        never a crash."""
        while n > len(self._free) and self._cached:
            victim = next(iter(self._cached))
            # the prefix cache drops the victim's trie node AND its subtree
            # (descendants of an unreferenced prefix are unreferenced too);
            # without a registered cache the parked page reclaims alone
            pages = self.evict_cb(victim) if self.evict_cb is not None \
                else [victim]
            for p in pages:
                self._reclaim(p)
        if n > len(self._free):
            raise OutOfPages(
                f"requested {n} KV pages with {len(self._free)} free "
                f"(pool: {self.pages_total}, cached: {len(self._cached)}); "
                f"admission should have back-pressured or preempted first")
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        for p in pages:
            self._rc[p] = 1
        self.pages_allocated += len(pages)
        self._min_free = min(self._min_free, len(self._free))
        return pages

    def retain(self, pages) -> None:
        """Add a reference to already-allocated pages (block-table fork /
        prefix-cache hit). A parked cached page leaves the evictable set —
        it is live again."""
        for p in pages:
            if not (0 < p < self.geometry.num_pages):
                raise ValueError(f"retaining invalid page id {p}")
            if p in self._free_set:
                raise ValueError(f"retain of free page {p}")
            if self._rc[p] == 0:
                self._cached.pop(p, None)    # parked -> live
            self._rc[p] += 1

    def free(self, pages) -> None:
        """Drop one reference per page. A page whose last reference drops
        returns to the free list — unless the prefix cache registered it,
        in which case it parks in the cached set with its K/V intact."""
        drops = Counter(pages)
        for p, n in drops.items():
            if not (0 < p < self.geometry.num_pages):
                raise ValueError(f"freeing invalid page id {p}")
            if p in self._free_set or self._rc[p] < n:
                raise ValueError(
                    f"double free of page {p} ({n} drops against "
                    f"{self._rc[p]} held references)")
        for p in pages:
            self._rc[p] -= 1
            if self._rc[p] > 0:
                continue                     # another block table still holds it
            if p in self._registered:
                self._cached[p] = None       # park for future prefix hits
            else:
                self._free.append(p)
                self._free_set.add(p)

    # -- copy-on-write forks ------------------------------------------------
    def fork(self, pages: list[int], length: int) -> list[int]:
        """Fork a block table covering ``length`` context tokens: full
        pages are SHARED (refcount bump, zero bytes moved) and only the
        partial tail page — the one the fork will keep writing into — is
        copied onto a fresh page. Page-aligned contexts fork with no copy
        at all (the next append opens a fresh page anyway). Raises
        ``OutOfPages`` if the tail copy can't allocate (after cached-page
        reclaim); the caller falls back to an ordinary re-prefill."""
        ps = self.geometry.page_size
        if length < 1:
            raise ValueError(f"cannot fork an empty context ({length=})")
        n_ctx = -(-length // ps)
        if len(pages) < n_ctx:
            raise ValueError(
                f"fork needs {n_ctx} pages for {length} tokens, got {len(pages)}")
        tail_partial = (length % ps) != 0
        shared = pages[:n_ctx - 1] if tail_partial else pages[:n_ctx]
        self.retain(shared)
        forked = list(shared)
        if tail_partial:
            try:
                [tail] = self.alloc(1)
            except OutOfPages:
                self.free(shared)            # undo: fork must be atomic
                raise
            self.copy_page(pages[n_ctx - 1], tail)
            self.cow_copies += 1
            forked.append(tail)
        return forked

    def copy_page(self, src: int, dst: int) -> None:
        """Copy one page's K/V across every layer (the COW tail copy —
        rare host-side path, one fork at a time, never in the compiled
        step). The update runs through one jitted dynamic-update-slice
        with the pool DONATED, so on backends with buffer donation the
        copy really is one page's bytes in place; without donation (CPU)
        XLA falls back to a pool copy, which only the toy smoke pays.
        Page ids ride in as traced scalars — one compile covers every
        (src, dst) pair."""
        import jax
        import jax.numpy as jnp

        fn = _page_copy_fn(jax.default_backend())
        for kv in self.pools:
            for key in ("k", "v"):
                kv[key] = fn(kv[key], jnp.int32(src), jnp.int32(dst))

    # -- prefix-cache registration ------------------------------------------
    def register_cached(self, page: int) -> None:
        """Mark a page as held by the prefix cache: when its refcount
        drops to zero it parks (K/V preserved, evictable) instead of
        returning to the free list."""
        if not (0 < page < self.geometry.num_pages):
            raise ValueError(f"registering invalid page id {page}")
        if page in self._free_set:
            raise ValueError(f"registering free page {page}")
        self._registered.add(page)
        if self._rc[page] == 0:
            self._cached[page] = None

    def unregister_cached(self, page: int) -> None:
        """Drop a page's prefix-cache registration (trie reset): a parked
        page returns to the free list immediately; a live page simply
        stops parking when its last reference drops."""
        self._registered.discard(page)
        if page in self._cached:
            del self._cached[page]
            self._free.append(page)
            self._free_set.add(page)

    def _reclaim(self, page: int) -> None:
        """Eviction: un-register a parked rc-0 page and return it to the
        free list (allocator pressure path; the trie entry is already
        gone)."""
        if self._rc[page] != 0 or page not in self._registered:
            raise ValueError(
                f"reclaiming page {page} that is live (rc={self._rc[page]}) "
                f"or unregistered")
        self._registered.discard(page)
        self._cached.pop(page, None)
        self._free.append(page)
        self._free_set.add(page)

    def update_pools(self, new_pools) -> None:
        """Store the updated pools returned by a compiled step (the step
        donates the old buffers, so the engine must never reuse them)."""
        self.pools = list(new_pools)

    def pools_alive(self) -> bool:
        """False when any pool buffer was deleted — a dispatch that donated
        the pools and then failed consumed them mid-execution, so replaying
        against this cache is impossible (the supervisor must rebuild)."""
        for kv in self.pools:
            for arr in kv.values():
                if getattr(arr, "is_deleted", lambda: False)():
                    return False
        return True

    def consume_pools(self) -> None:
        """Delete every pool buffer — what a real accelerator fault does to
        donated inputs mid-execution (the write-side dual of
        :meth:`pools_alive`). Only the ``serving:engine`` fault-injection
        path calls this; recovery is a supervisor pool rebuild."""
        for kv in self.pools:
            for arr in kv.values():
                try:
                    arr.delete()
                except Exception:
                    pass

    def assert_quiescent(self, block_tables=None) -> None:
        """Leak audit for an idle pool, refcount-aware: every allocatable
        page is either on the free list or parked at refcount 0 by the
        prefix cache (its K/V deliberately preserved for future hits), no
        page holds a live reference, the free-list mirror set agrees with
        the list exactly, every listed page id is a valid non-scratch pool
        index, and (when the engine hands its block tables over) no table
        entry references anything but the reserved scratch page 0. Raises
        ``AssertionError`` naming the violation — the chaos-soak /
        eviction / supervisor-restart tests call this after every run, so
        a single leaked page or refcount, or a diverged mirror, fails
        loudly instead of surfacing later as an allocator mystery."""
        live = [p for p in range(1, self.geometry.num_pages) if self._rc[p]]
        if live:
            raise AssertionError(
                f"KV page leak: {len(live)} pages still hold live "
                f"references on an idle pool (first ids: {live[:8]}, "
                f"refcounts: {[self._rc[p] for p in live[:8]]})")
        accounted = len(self._free) + len(self._cached)
        if accounted != self.pages_total:
            raise AssertionError(
                f"KV page leak: free ({len(self._free)}) + cached "
                f"({len(self._cached)}) != allocatable ({self.pages_total})")
        stray = sorted(set(self._free) & set(self._cached))
        if stray:
            raise AssertionError(
                f"pages on the free list AND in the cached set: {stray}")
        if len(self._free) != len(self._free_set) or \
                set(self._free) != self._free_set:
            raise AssertionError(
                f"free-list/mirror-set divergence: list holds "
                f"{len(self._free)} entries ({len(set(self._free))} unique), "
                f"mirror holds {len(self._free_set)}")
        bad = sorted(p for p in self._free
                     if not (0 < p < self.geometry.num_pages))
        if bad:
            raise AssertionError(f"free list holds invalid page ids {bad} "
                                 f"(pool has {self.geometry.num_pages} pages, "
                                 f"page 0 reserved)")
        if block_tables is not None:
            import numpy as np

            nz = np.flatnonzero(np.asarray(block_tables))
            if nz.size:
                raise AssertionError(
                    f"{nz.size} block-table entries still reference "
                    f"non-scratch pages on an idle engine (first flat "
                    f"indices: {nz[:8].tolist()})")


_PAGE_COPY_FNS: dict = {}


def _page_copy_fn(backend: str):
    """Jitted single-page pool copy, donated where the backend supports
    aliasing (donating on CPU only buys a warning per call)."""
    fn = _PAGE_COPY_FNS.get(backend)
    if fn is None:
        import jax

        def _copy(pool, src, dst):
            page = jax.lax.dynamic_index_in_dim(pool, src, axis=1)
            return jax.lax.dynamic_update_slice_in_dim(pool, page, dst, axis=1)

        donate = () if backend == "cpu" else (0,)
        fn = jax.jit(_copy, donate_argnums=donate)
        _PAGE_COPY_FNS[backend] = fn
    return fn


class OutOfPages(RuntimeError):
    """The page pool cannot satisfy an allocation; scheduler-level signal."""

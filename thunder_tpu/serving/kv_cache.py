"""Block-allocated paged KV cache for the serving engine.

One shared pool of fixed-size pages per layer (``(kv_heads, num_pages,
page_size, head_dim)`` K and V arrays) plus a host-side free list and
per-request block tables — the vLLM PagedAttention memory model, TPU-first:
requests at wildly different sequence lengths share one device allocation,
so the compiled decode step has ONE shape regardless of who is resident
(no per-request recompiles, no per-request max_len buffers).

Page 0 is reserved as the scratch page: it is never allocated, inactive
decode slots write their (discarded) K/V there, and unallocated block-table
entries point at it — every table entry is always a valid pool index, which
is what lets the Pallas kernel's scalar-prefetch index map run unguarded.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PageGeometry:
    """Static pool geometry; everything the compiled step's shapes depend on."""

    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int       # tokens per page
    num_pages: int       # pool pages per layer, INCLUDING the reserved page 0
    pages_per_request: int  # block-table width (max context / page_size)

    @property
    def max_context(self) -> int:
        return self.pages_per_request * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` of context."""
        return -(-n_tokens // self.page_size)


class PagedKVCache:
    """Device page pools + host free list.

    ``pools`` is a list (per layer) of ``{"k": array, "v": array}`` with
    shape ``(kv_heads, num_pages, page_size, head_dim)``. The arrays are
    functional: the engine passes them into the compiled step (donated) and
    stores the returned updated pools back via :meth:`update_pools`.
    """

    def __init__(self, geometry: PageGeometry, dtype):
        import jax.numpy as jnp

        g = geometry
        if g.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        self.geometry = g
        self.dtype = dtype
        shape = (g.kv_heads, g.num_pages, g.page_size, g.head_dim)
        self.pools = [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
                      for _ in range(g.n_layers)]
        # LIFO free list: recently-freed pages are re-served first (their
        # pool region is likeliest still warm in any cache hierarchy); the
        # mirror set keeps free()'s double-free check O(1) per page (a list
        # scan is O(pool) — quadratic on the completion/eviction hot path)
        self._free: list[int] = list(range(g.num_pages - 1, 0, -1))
        self._free_set: set[int] = set(self._free)
        self._min_free = len(self._free)  # high-water tracking (peak usage)

    # -- allocation ---------------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_total(self) -> int:
        """Allocatable pages (the reserved scratch page doesn't count)."""
        return self.geometry.num_pages - 1

    @property
    def peak_pages_used(self) -> int:
        return self.pages_total - self._min_free

    def utilization(self) -> float:
        return 1.0 - self.pages_free / self.pages_total

    def reset_peak(self) -> None:
        """Restart high-water tracking (benchmarks: exclude warmup)."""
        self._min_free = len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        """Pop ``n`` pages off the free list. Raises ``OutOfPages`` when the
        pool can't satisfy the request — the scheduler turns that into
        admission back-pressure or preemption, never a crash."""
        if n > len(self._free):
            raise OutOfPages(
                f"requested {n} KV pages with {len(self._free)} free "
                f"(pool: {self.pages_total}); admission should have "
                f"back-pressured or preempted first")
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        self._min_free = min(self._min_free, len(self._free))
        return pages

    def free(self, pages) -> None:
        """Return pages to the free list (eviction / completion path)."""
        for p in pages:
            if not (0 < p < self.geometry.num_pages):
                raise ValueError(f"freeing invalid page id {p}")
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)
        self._free_set.update(pages)

    def update_pools(self, new_pools) -> None:
        """Store the updated pools returned by a compiled step (the step
        donates the old buffers, so the engine must never reuse them)."""
        self.pools = list(new_pools)

    def pools_alive(self) -> bool:
        """False when any pool buffer was deleted — a dispatch that donated
        the pools and then failed consumed them mid-execution, so replaying
        against this cache is impossible (the supervisor must rebuild)."""
        for kv in self.pools:
            for arr in kv.values():
                if getattr(arr, "is_deleted", lambda: False)():
                    return False
        return True

    def consume_pools(self) -> None:
        """Delete every pool buffer — what a real accelerator fault does to
        donated inputs mid-execution (the write-side dual of
        :meth:`pools_alive`). Only the ``serving:engine`` fault-injection
        path calls this; recovery is a supervisor pool rebuild."""
        for kv in self.pools:
            for arr in kv.values():
                try:
                    arr.delete()
                except Exception:
                    pass

    def assert_quiescent(self, block_tables=None) -> None:
        """Leak audit for an idle pool: every allocatable page is back on
        the free list, the mirror set agrees with the list exactly, every
        listed page id is a valid non-scratch pool index, and (when the
        engine hands its block tables over) no table entry references
        anything but the reserved scratch page 0. Raises ``AssertionError``
        naming the violation — the chaos-soak / eviction / supervisor-
        restart tests call this after every run, so a single leaked page or
        a diverged mirror fails loudly instead of surfacing later as an
        allocator mystery."""
        leaked = self.pages_total - len(self._free)
        if leaked:
            raise AssertionError(
                f"KV page leak: {leaked} of {self.pages_total} pages still "
                f"allocated on an idle pool")
        if len(self._free) != len(self._free_set) or \
                set(self._free) != self._free_set:
            raise AssertionError(
                f"free-list/mirror-set divergence: list holds "
                f"{len(self._free)} entries ({len(set(self._free))} unique), "
                f"mirror holds {len(self._free_set)}")
        bad = sorted(p for p in self._free
                     if not (0 < p < self.geometry.num_pages))
        if bad:
            raise AssertionError(f"free list holds invalid page ids {bad} "
                                 f"(pool has {self.geometry.num_pages} pages, "
                                 f"page 0 reserved)")
        if block_tables is not None:
            import numpy as np

            nz = np.flatnonzero(np.asarray(block_tables))
            if nz.size:
                raise AssertionError(
                    f"{nz.size} block-table entries still reference "
                    f"non-scratch pages on an idle engine (first flat "
                    f"indices: {nz[:8].tolist()})")


class OutOfPages(RuntimeError):
    """The page pool cannot satisfy an allocation; scheduler-level signal."""

"""In-graph sampling for the serving engine (ROADMAP 5(c) / 2(c)).

The decode program's epilogue: per-slot sampling parameters and RNG keys
ride into the compiled step as plain arrays, and the program emits sampled
TOKEN IDS — the scheduler never sees logits, which is the prerequisite for
the fully device-side token loop (a stop-condition word + batched token
drain can only exist once the host stops argmax-ing every step).

Design constraints, in order:

- **Greedy is the ``temperature == 0`` degenerate case of the SAME
  program.** A greedy slot's token is ``argmax(logits)`` computed in-graph
  — bit-identical to the host argmax it replaces — so every existing
  token-identity-vs-``llama.generate`` pin survives with sampling compiled
  in. One program serves mixed greedy/sampled batches.
- **Sort-free filtering.** ``top_k`` and ``top_p`` are implemented as
  threshold masks found by fixed-iteration bisection (count / probability-
  mass predicates), not by sorting the vocabulary: a V-length sort is the
  classic TPU sampling bottleneck, while bisection is a handful of
  elementwise-compare+reduce passes with a compile-time trip count.
  Top-k bisects on the RAW logits (the top-k set is temperature-invariant)
  so the threshold resolution doesn't degrade at small temperatures.
  Ties at the converged threshold are all admitted (the mask keeps *at
  least* k / *at least* mass p) — same tie semantics either side of the
  threshold as a sort-based cutoff, documented rather than hidden.
- **Batch-composition-independent streams.** Each slot's randomness is a
  counter-based hash ``mix(seed, counter, vocab_index)`` — seed from the
  request's :class:`SamplingParams`, counter = tokens sampled so far — so
  a request's token stream is a pure function of (seed, counter, logits):
  reproducible across recompiles, engine restarts, preemption
  (recompute-on-resume replays the same counters), and whatever else
  happens to share the batch. The mix is the murmur3 finalizer over
  independently Weyl-multiplied inputs: ONE fused elementwise pass over
  the (slots, vocab) grid, where per-slot keyed threefry uniforms would
  cost a separate V-wide sweep per slot (measured 4x the whole epilogue's
  cost on the CPU smoke geometry).
- **Gumbel-max draw.** The sample itself is ``argmax(masked_logits + g)``
  with iid Gumbel noise — the ``ops.multinomial`` trick, fused into the
  decode epilogue instead of dispatched as its own program.
"""

from __future__ import annotations

from dataclasses import dataclass

from thunder_tpu import ops
from thunder_tpu.core import dtypes

# masked-out vocabulary entries: finite (NaN-free through softmax/add) but
# below any real logit by enough that Gumbel noise can never resurrect one
_MASKED = -1e30

# bisection trip counts (compile-time unrolled). Top-k runs on raw logits
# (range ~1e2), top-p on probabilities in [0, 1]; 18 halvings put the
# threshold within ~range * 4e-6 of the exact order statistic — only
# values tied at that resolution can be admitted past k / past mass p,
# and each extra iteration is a full (S, V) compare+reduce pass, so the
# count is the sampler's cost knob (the whole epilogue must stay noise
# next to the lm_head matmul even on toy geometries).
_TOPK_ITERS = 18
_TOPP_ITERS = 18


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature == 0`` selects greedy decoding (the default) — the
    in-graph sampler degenerates to ``argmax``. ``top_k == 0`` disables
    top-k filtering; ``top_p == 1.0`` disables nucleus filtering. ``seed``
    pins the request's RNG stream (reproducible run-to-run); ``None``
    derives a stream from the process-unique request id instead (distinct
    per request, NOT reproducible across runs).
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int | None = None

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def stream_seed(self, request_id: int) -> int:
        """The uint32 seed of this request's RNG stream (explicit seed, or
        a request-id-derived one — Weyl-scrambled so adjacent ids don't
        get adjacent threefry keys)."""
        if self.seed is not None:
            return self.seed & 0xFFFFFFFF
        return (request_id * 0x9E3779B1 + 0x7F4A7C15) & 0xFFFFFFFF

    def fork(self, branch: int) -> "SamplingParams":
        """Sampling params for best-of-N branch ``branch`` (1-based for
        clones): the same filtering config on a shifted seed, so each
        branch draws an independent stream while staying reproducible
        when the parent's seed is pinned."""
        seed = None if self.seed is None else (self.seed + branch) & 0xFFFFFFFF
        return SamplingParams(temperature=self.temperature, top_k=self.top_k,
                              top_p=self.top_p, seed=seed)


GREEDY = SamplingParams()


def _u32(value: int):
    return ops.full((), value, dtype=dtypes.uint32)


def _gumbel(rng, V: int):
    """Per-slot iid Gumbel noise over the vocabulary from raw
    ``[seed, counter]`` uint32 rows: murmur3-finalizer avalanche over the
    Weyl-multiplied (seed, counter, vocab_index) triple, mapped through
    the top 24 bits to a (0, 1) uniform, then the double-log transform.
    Pure elementwise — one fused pass over (S, V) — and a pure function
    of the key row, so streams never depend on batch composition."""
    S = rng.shape[0]
    seed = ops.getitem(rng, (slice(None), 0))              # (S,)
    ctr = ops.getitem(rng, (slice(None), 1))
    v = ops.convert_element_type(ops.arange(0, V, dtype=dtypes.int32),
                                 dtypes.uint32)
    h = ops.bitwise_xor(ops.mul(seed, _u32(0x9E3779B1)),
                        ops.mul(ctr, _u32(0x85EBCA77)))
    h = ops.bitwise_xor(ops.reshape(h, (S, 1)),
                        ops.mul(ops.reshape(v, (1, V)), _u32(0xC2B2AE3D)))
    h = ops.bitwise_xor(h, ops.shift_right(h, 16))
    h = ops.mul(h, _u32(0x85EBCA6B))
    h = ops.bitwise_xor(h, ops.shift_right(h, 13))
    h = ops.mul(h, _u32(0xC2B2AE35))
    h = ops.bitwise_xor(h, ops.shift_right(h, 16))
    u = ops.add(ops.mul(ops.convert_element_type(ops.shift_right(h, 8),
                                                 dtypes.float32),
                        1.0 / (1 << 24)), 1e-9)            # (0, 1)
    return ops.neg(ops.log(ops.neg(ops.log(u))))


def _topk_threshold(l32, k_col):
    """Largest threshold t with ``count(l >= t) >= k``, per row, by
    bisection (sort-free). Returns the (S, 1) threshold; masking
    ``l >= t`` keeps the k largest entries plus any ties at t."""
    lo = ops.sub(ops.amin(l32, dim=-1, keepdim=True), 1.0)   # count == V >= k
    hi = ops.add(ops.amax(l32, dim=-1, keepdim=True), 1.0)   # count == 0 <  k
    for _ in range(_TOPK_ITERS):
        mid = ops.mul(ops.add(lo, hi), 0.5)
        cnt = ops.sum(ops.convert_element_type(ops.ge(l32, mid),
                                               dtypes.float32),
                      dim=-1, keepdim=True)
        keep = ops.ge(cnt, k_col)            # can the threshold be raised?
        lo = ops.where(keep, mid, lo)
        hi = ops.where(keep, hi, mid)
    return lo


def _topp_threshold(probs, p_col):
    """Largest probability threshold t with ``sum(probs[probs >= t]) >=
    top_p``, per row, by bisection on [0, 1] (sort-free nucleus cutoff).
    Masking ``probs >= t`` keeps the smallest high-probability set with
    at least ``top_p`` mass (plus ties at t)."""
    zero = ops.zeros_like(p_col)
    lo = zero                                  # mass == 1 >= top_p
    hi = ops.add(zero, 1.0 + 1e-6)             # mass == 0 <  top_p
    for _ in range(_TOPP_ITERS):
        mid = ops.mul(ops.add(lo, hi), 0.5)
        mass = ops.sum(ops.where(ops.ge(probs, mid), probs, zero),
                       dim=-1, keepdim=True)
        keep = ops.ge(mass, p_col)
        lo = ops.where(keep, mid, lo)
        hi = ops.where(keep, hi, mid)
    return lo


def sample_tokens(logits, temps, top_ks, top_ps, rng):
    """Traced sampling epilogue: ``(S, V)`` logits -> ``(S,)`` int32 tokens.

    ``temps`` (S,) f32, ``top_ks`` (S,) int32 (0 disables), ``top_ps``
    (S,) f32 (1 disables), ``rng`` (S, 2) uint32 — each row the
    ``[stream_seed, counter]`` key of the slot's hash-based RNG stream.
    Rows with ``temps == 0`` return the
    plain in-graph ``argmax`` (greedy), bit-identical to the host argmax
    this epilogue replaces; the sampled path for those rows is computed
    and discarded by ``where`` (O(S*V) elementwise work, noise next to
    the lm_head matmul that produced the logits).
    """
    S, V = logits.shape
    l32 = ops.convert_element_type(logits, dtypes.float32)
    greedy = ops.convert_element_type(ops.argmax(l32, dim=-1), dtypes.int32)

    # top-k threshold mask on the RAW logits (temperature-invariant set)
    k_col = ops.convert_element_type(ops.reshape(top_ks, (S, 1)),
                                     dtypes.float32)
    need_k = ops.logical_and(ops.ge(k_col, 1.0), ops.lt(k_col, float(V)))
    k_mask = ops.ge(l32, _topk_threshold(l32, k_col))
    masked = ops.where(ops.logical_and(need_k, ops.logical_not(k_mask)),
                       ops.full((), _MASKED, dtype=dtypes.float32), l32)

    # temperature scaling (sampled path only; the floor keeps the scaled
    # range bounded so downstream float math stays well-conditioned —
    # temperatures at or below it are what the greedy path is for)
    t_col = ops.clamp(ops.reshape(temps, (S, 1)), min=1e-3)
    scaled = ops.true_divide(masked, t_col)

    # nucleus (top-p) threshold mask on the scaled distribution
    p_col = ops.reshape(top_ps, (S, 1))
    need_p = ops.lt(p_col, 1.0)
    probs = ops.softmax(scaled, dim=-1, dtype=dtypes.float32)
    p_mask = ops.ge(probs, _topp_threshold(probs, p_col))
    scaled = ops.where(ops.logical_and(need_p, ops.logical_not(p_mask)),
                       ops.full((), _MASKED, dtype=dtypes.float32), scaled)

    # Gumbel-max categorical draw, one independent hash stream per slot
    sampled = ops.convert_element_type(
        ops.argmax(ops.add(scaled, _gumbel(rng, V)), dim=-1), dtypes.int32)

    return ops.where(ops.gt(temps, 0.0), sampled, greedy)

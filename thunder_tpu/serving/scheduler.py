"""Continuous (in-flight) batching scheduler over the paged KV cache.

The serving runtime ROADMAP item 1 calls for: many concurrent streams at
different sequence lengths served by ONE compiled decode program.

- **Admission**: queued requests join a free decode slot when the page pool
  can cover their first prefill chunk; otherwise the queue back-pressures
  (nothing crashes — pages are the capacity unit). Admission is
  priority-ordered (highest first, FIFO among equals), the queue is
  optionally bounded (``max_queue``: overflow sheds the lowest-priority
  queued request or rejects the newcomer, typed
  :class:`~thunder_tpu.serving.errors.AdmissionRejected`), and a request
  whose page demand exceeds the TOTAL pool fails at ``submit()`` with
  :class:`~thunder_tpu.serving.errors.InfeasibleRequest` instead of
  queueing forever.
- **Request SLOs**: ``submit(deadline_s=, priority=)``. Every engine
  iteration sheds expired queued requests and evicts expired residents
  with :class:`~thunder_tpu.serving.errors.DeadlineExceeded`
  (``serving.deadline_misses``); shedding of any kind counts
  ``serving.shed_requests`` and the rolling on-time completion ratio is
  the ``serving.slo_attainment`` gauge.
- **Decode-first with chunked prefill interleaving**: every engine
  iteration runs one batched decode step over all resident requests, plus
  at most ONE prefill chunk of the head-of-line prefilling request — long
  prompts cannot stall in-flight decodes for more than a chunk.
- **Continuous batching**: requests join and leave the decode batch
  mid-flight. Completion (or EOS) frees the request's pages immediately;
  the slot admits the next queued request on the same compiled program.
- **Preemption**: when the pool runs dry mid-decode, the lowest-priority
  newest resident request is evicted back to the queue (recompute-on-
  resume: its generated tokens re-prefill as prompt) —
  ``serving.preempted_requests`` counts these.
- **Dispatch**: the decode step is bound (``bind()``, zero-guard) and runs
  under the ``step`` + ``serving:decode`` fault domains with retry (prefill
  under ``serving:prefill``) — a transient injected or XLA fault re-runs
  the same step; kernel crashes still take the normal quarantine path
  inside the bound call. A failure that CONSUMED the donated page pools
  mid-execution (the ``serving:engine`` domain simulates this) escalates
  as :class:`~thunder_tpu.serving.errors.EngineFault`: in-place retry is
  impossible, and the :class:`~thunder_tpu.serving.supervisor
  .EngineSupervisor` restart — pool rebuild + re-prefill of every
  in-flight request via :meth:`ServingEngine.rebuild_after_fault` — is the
  engine-level fallback rung.

- **Always-on lifecycle tracing**: every request's phase chain (submitted
  → queued → admitted → prefill chunk(s) → decode residency → preempt /
  restart re-prefill → complete/shed) is recorded as spans + events
  through ``observe.registry`` — which feeds the bounded flight ring
  (``observe.flight``) even when the registry is disabled, so a fault
  leaves a black box. Each iteration also records scheduler spans
  (``schedule`` host work vs ``decode_dispatch``); the Perfetto exporter
  renders per-request tracks, a scheduler track, and counter tracks.

- **In-graph sampling**: every request carries
  :class:`~thunder_tpu.serving.sampling.SamplingParams`; the compiled
  decode step samples temperature/top-k/top-p tokens IN-GRAPH (per-slot
  parameter rows + threefry keys, sort-free threshold masking, Gumbel-max
  draw) and the scheduler reads token ids, never logits. Greedy is the
  ``temperature == 0`` degenerate case of the same program — bit-identical
  to the host argmax it replaced, so token-identity-vs-``generate()`` pins
  hold. Every request's FIRST token comes from a decode *replay* step (the
  last prompt token re-fed with its K/V write redirected to the scratch
  page), so prefill carries no lm_head at all and first tokens ride the
  batched decode program like every other token.
- **Best-of-N via copy-on-write forks**: ``submit(best_of=N)`` prefills
  ONCE; when the primary's prompt is resident, N-1 clones fork its block
  table — full pages shared by refcount, only the partial tail page
  copied — and branch with independent RNG streams
  (``SamplingParams.fork``). A clone that can't fork yet (no free slot /
  no tail page) waits on the primary and spills to the ordinary queue if
  the primary terminates first.
- **Cross-request prefix cache** (``prefix_cache=True``): admission probes
  a page-granularity token trie
  (:class:`~thunder_tpu.serving.prefix_cache.PrefixCache`) with the
  prompt, prefill starts at the first uncached page, and completed
  requests donate their full prompt pages back. Cached pages are parked
  at refcount 0 — evicted oldest-first by the allocator under page
  pressure, so the cache can never starve live traffic. A warm hit
  collapses TTFT to one tail-chunk prefill
  (``serving.prefix_hit_rate`` / ``serving.cached_pages``).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime import faults as _faults
from thunder_tpu.runtime import quarantine as _quarantine
from thunder_tpu.runtime import retry as _retry
from thunder_tpu.serving.errors import (
    AdmissionRejected,
    DeadlineExceeded,
    EngineFault,
    EngineStallError,
    InfeasibleRequest,
    RestartState,
    ShardingGeometryError,
)
from thunder_tpu.serving.kv_cache import OutOfPages, PagedKVCache, PageGeometry
from thunder_tpu.serving.prefix_cache import PrefixCache
from thunder_tpu.serving.runner import PagedLlamaRunner
from thunder_tpu.serving.sampling import GREEDY, SamplingParams

QUEUED, PREFILL, DECODE, DONE, SHED = \
    "queued", "prefill", "decode", "done", "shed"

# request ids are PROCESS-unique (not per-engine): the flight recorder and
# the Perfetto per-request tracks key on the id, and a bench that builds a
# warm engine and a timed engine must not interleave two "request 0"s on
# one timeline
_REQUEST_IDS = itertools.count()

# process-unique engine ids ("e0", "e1", ...): the label value that keys
# every engine's metrics/events/spans so N engines in one process never
# clobber each other's series (the fleet-observatory contract)
_ENGINE_IDS = itertools.count()


def _as_tp_mesh(mesh, cfg):
    """Normalize the engine's ``mesh=`` argument (None, an int tp degree,
    or a ``TensorParallelMesh``) and validate the model config against it
    with typed errors — a bad split must fail HERE, not as an opaque XLA
    partitioner error three layers down."""
    if mesh is None:
        return None
    from thunder_tpu.distributed.gspmd import TensorParallelMesh
    from thunder_tpu.models.llama import TP_COLUMN_PATTERNS, TP_ROW_PATTERNS

    if isinstance(mesh, int):
        mesh = TensorParallelMesh(tp=mesh,
                                  column_patterns=TP_COLUMN_PATTERNS,
                                  row_patterns=TP_ROW_PATTERNS)
    if mesh.tp <= 1:
        return None
    for name, n in (("n_heads", cfg.n_heads), ("kv_heads", cfg.kv_heads),
                    ("intermediate_size", cfg.intermediate_size)):
        if n % mesh.tp != 0:
            raise ShardingGeometryError(
                f"config {cfg.name}: {name}={n} not divisible by "
                f"tp={mesh.tp}", kv_heads=cfg.kv_heads, tp=mesh.tp)
    return mesh


@dataclass(eq=False)  # identity semantics: requests live in slot lists
class Request:
    """One generation request and its full lifecycle state."""

    prompt: np.ndarray                  # original prompt token ids (1-D int32)
    max_new_tokens: int
    request_id: int
    eos_id: int | None = None
    priority: int = 0                   # higher = more important (shed last)
    deadline_at: float | None = None    # absolute perf_counter deadline
    submitted_s: float = 0.0
    state: str = QUEUED
    error: BaseException | None = None  # set when state == SHED
    pages: list = field(default_factory=list)   # allocated page ids, in order
    prefilled: int = 0                  # work-prompt tokens written so far
    length: int = 0                     # context tokens written into the cache
    next_token: int | None = None       # sampled, not yet fed to decode
    generated: list = field(default_factory=list)
    ttft_s: float | None = None
    finished_s: float | None = None
    decode_start_s: float | None = None
    preemptions: int = 0
    restarts: int = 0                   # supervisor crash-recovery re-admits
    admit_seq: int = -1                 # admission order (preemption victim pick)
    pages_version: int = 0              # bumped when ``pages`` changes
    # in-graph sampling: per-request params + derived uint32 stream seed
    sampling: SamplingParams = GREEDY
    stream_seed: int = 0
    _replay: bool = False               # next decode step re-feeds the last
    #                                     prompt token (write -> scratch) to
    #                                     sample the FIRST token in-graph
    # best-of-N copy-on-write forks
    fork_parent: "Request | None" = None
    fork_pending: list = field(default_factory=list)  # clones awaiting fork
    fork_group: list = field(default_factory=list)    # primary + clones
    # cross-request prefix cache
    prefix_hit_tokens: int = 0          # prompt tokens served from the trie
    # lifecycle tracing (flight recorder + Perfetto request tracks)
    submitted_us: float = 0.0           # observe-epoch submit timestamp
    queued_ms: float = 0.0              # total time spent queued (incl. resumes)
    prefill_chunks: int = 0             # prefill dispatches (incl. re-prefill)
    _phase: str = ""                    # open lifecycle phase span, if any
    _phase_t0_us: float = 0.0

    @property
    def work_prompt(self) -> np.ndarray:
        """What prefill must write: the original prompt plus any tokens
        generated before a preemption or engine restart
        (recompute-on-resume)."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    @property
    def done(self) -> bool:
        return self.state == DONE

    @property
    def failed(self) -> bool:
        """True when the engine shed this request (``error`` says why:
        ``DeadlineExceeded`` or ``AdmissionRejected``)."""
        return self.state == SHED

    def output(self) -> np.ndarray:
        return np.asarray(self.generated, np.int32)


class ServingEngine:
    """Continuous-batching serving runtime for a Llama-family model.

    >>> eng = ServingEngine(params, cfg, max_slots=8, page_size=16,
    ...                     max_context=256, n_layers=2)
    >>> r = eng.submit([1, 2, 3], max_new_tokens=16, deadline_s=30.0)
    >>> eng.drain()
    >>> r.output()

    ``max_slots`` is the compiled decode batch width; ``num_pages`` sizes
    the shared pool (default: full residency for every slot — shrink it to
    exercise admission back-pressure and preemption); ``max_queue`` bounds
    the admission queue (``None`` = unbounded; overflow sheds by priority).
    """

    def __init__(self, params, cfg, *, max_slots: int = 8, page_size: int = 16,
                 num_pages: int | None = None, max_context: int | None = None,
                 prefill_chunk: int | None = None, n_layers: int | None = None,
                 max_queue: int | None = None, executors=None,
                 retry_policy=None, block_fusion=None,
                 prefix_cache: bool = False,
                 launch_budget_per_layer: float | None = None,
                 mesh=None, engine_id: str | None = None):
        # tensor-parallel serving (GSPMD): `mesh` is an int tp degree or a
        # distributed.gspmd.TensorParallelMesh. Params are committed to the
        # Megatron column/row plan, the paged pool is sharded by kv-head,
        # and the runner's jitted step compiles ONE SPMD program around the
        # committed shardings (donation preserved — in/out pool shardings
        # match). Step inputs stay host arrays (replicated).
        # engine identity first: every emission below this line is labeled
        self.engine_id = engine_id if engine_id is not None \
            else f"e{next(_ENGINE_IDS)}"
        self.obs = _observe.labeled(engine=self.engine_id)
        self.mesh = _as_tp_mesh(mesh, cfg)
        if self.mesh is not None:
            from thunder_tpu.distributed.gspmd import shard_params

            params = shard_params(params, self.mesh)
        self.params = params
        self.cfg = cfg
        n_layers_eff = n_layers if n_layers is not None else cfg.n_layers
        max_context = int(max_context or cfg.max_seq_len)
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        # prefill chunk ladder: powers-of-two multiples of the page size —
        # chunk starts stay page-aligned by construction, and ragged prompt
        # lengths compile at most len(ladder) prefill programs
        cap = int(prefill_chunk or min(max_context, 512))
        cap = max(page_size, (cap // page_size) * page_size)
        ladder, b = [], page_size
        while b < cap:
            ladder.append(b)
            b *= 2
        ladder.append(cap)
        from thunder_tpu.data import LengthBucketer

        self.chunker = LengthBucketer(ladder)
        self.max_chunk = ladder[-1]
        # align the context window to the chunk ladder top so a fully
        # chunk-padded prefill can never outrun the block table
        max_context = -(-max_context // self.max_chunk) * self.max_chunk
        self.max_context = max_context
        pages_per_req = -(-max_context // page_size)
        if num_pages is None:
            num_pages = max_slots * pages_per_req + 1  # + reserved page 0
        geometry = PageGeometry(
            n_layers=n_layers_eff, kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            page_size=page_size, num_pages=int(num_pages),
            pages_per_request=pages_per_req)
        self.geom = geometry
        # the typed restart state: everything a supervisor rebuild needs to
        # recreate the pool EXACTLY — geometry + dtype + mesh — carried on
        # every EngineFault so recovery is sharding-identical
        self._restart_state = RestartState(
            geometry=geometry, dtype=cfg.dtype.jax, mesh=self.mesh)
        self.cache = PagedKVCache(geometry, cfg.dtype.jax, sharding=self.mesh)
        # cross-request prefix cache (opt-in): completed prompts donate
        # their full pages into a token trie; admission probes it
        self.prefix = PrefixCache(self.cache) if prefix_cache else None
        self.runner = PagedLlamaRunner(
            cfg, geometry, n_layers=n_layers, executors=executors,
            block_fusion=block_fusion,
            launch_budget_per_layer=launch_budget_per_layer, mesh=self.mesh,
            engine_id=self.engine_id)
        if self.mesh is not None:
            from thunder_tpu.distributed.gspmd import mesh_descriptor

            md = mesh_descriptor(self.mesh)
            self.obs.set_gauge("serving.tp_degree", md["tp_degree"])
            self.obs.event("serving_mesh", phase="build", **md)
        self.max_slots = int(max_slots)
        self.max_queue = max_queue
        self.slots: list[Request | None] = [None] * self.max_slots
        self.queue: deque[Request] = deque()
        self.completed: list[Request] = []
        self.shed: list[Request] = []
        self.admitting = True           # stop_admissions() flips this
        self._admits = itertools.count()
        self._step_count = 0
        self._slo_attained = 0          # on-time completions
        self._slo_total = 0             # terminal requests (done + shed)
        self._slo_resets = 0            # reset_slo_window() generation
        self.decode_rebinds = 0         # quarantine-forced re-binds (health
        #                                 reads this registry-independently)
        # serving is latency-sensitive: quick retries, no long backoff
        self._retry_policy = retry_policy or _retry.RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=1.0)
        self._decode_bound = None
        self._bound_epoch = -1
        # persistent decode-step input buffers: rebuilt rows only for slots
        # whose state changed (the block-table row is cached per request) —
        # per-step host work stays O(active), not O(slots * table width)
        S = self.max_slots
        self._np_tokens = np.zeros((S, 1), np.int32)
        self._np_bt = np.zeros((S, pages_per_req), np.int32)
        self._np_len = np.ones(S, np.int32)
        self._np_wp = np.zeros(S, np.int32)
        self._bt_slot_version: list = [None] * S
        # per-slot sampling rows fed to the in-graph sampler: temperature /
        # top-k / top-p plus a raw threefry key [stream_seed, counter].
        # Idle slots are greedy rows on the zero key (their token is
        # computed and discarded)
        self._np_temp = np.zeros(S, np.float32)
        self._np_topk = np.zeros(S, np.int32)
        self._np_topp = np.ones(S, np.float32)
        self._np_rng = np.zeros((S, 2), np.uint32)

    # -- public API ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: int | None = None, deadline_s: float | None = None,
               priority: int = 0, sampling: SamplingParams | None = None,
               best_of: int = 1) -> Request:
        """Enqueue a request. ``deadline_s`` is the SLO budget from now
        (expiry sheds the request with ``DeadlineExceeded``); ``priority``
        orders admission and shedding (higher survives longer).

        ``sampling`` selects the in-graph sampler's per-request config
        (default greedy). ``best_of=N`` runs N branches over ONE prefill:
        the primary prefills normally and N-1 clones fork its block table
        copy-on-write once the prompt is resident, each on an independent
        RNG stream (``sampling.fork``). Returns the primary; the whole
        group is ``request.fork_group``. Clones bypass the admission
        queue (they ride the primary) but count as ordinary requests
        everywhere else — slots, pages, SLO accounting, shedding.

        Raises ``InfeasibleRequest`` when the request could never run on
        this engine (capacity contract, checked up front — an infeasible
        prompt must not queue forever and wedge ``drain()``) and
        ``AdmissionRejected`` when admissions are stopped or the bounded
        queue sheds it."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if best_of < 1:
            raise ValueError(f"best_of must be >= 1, got {best_of}")
        sampling = GREEDY if sampling is None else sampling
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_context:
            raise InfeasibleRequest(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine context window ({self.max_context})",
                engine_id=self.engine_id)
        # worst-case page footprint: the larger of the final context and the
        # chunk-PADDED prefill high-water mark (the last chunk rounds up to
        # a ladder size, which can transiently need more pages than the
        # final context — e.g. a 33-token prompt prefills as one 64 chunk)
        worst = max(total, self._padded_prefill_len(total))
        if self.geom.pages_for(worst) > self.cache.pages_total:
            raise InfeasibleRequest(
                f"request needs up to {self.geom.pages_for(worst)} KV pages; "
                f"the pool only has {self.cache.pages_total} — enlarge "
                f"num_pages", engine_id=self.engine_id)
        now = time.perf_counter()

        def new_request(sp: SamplingParams, parent=None) -> Request:
            r = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                        request_id=next(_REQUEST_IDS), eos_id=eos_id,
                        priority=int(priority),
                        deadline_at=None if deadline_s is None
                        else now + float(deadline_s),
                        submitted_s=now, submitted_us=_observe._now_us(),
                        sampling=sp, fork_parent=parent)
            r.stream_seed = sp.stream_seed(r.request_id)
            # lifecycle edge 1: always in the flight ring, registry on/off
            self.obs.event("serving_submitted", request=r.request_id,
                           prompt_tokens=int(prompt.size),
                           max_new_tokens=int(max_new_tokens),
                           priority=r.priority, deadline_s=deadline_s,
                           best_of=best_of if parent is None else None,
                           fork_of=None if parent is None
                           else parent.request_id)
            self._phase_begin(r, QUEUED)
            return r

        req = new_request(sampling)
        if best_of > 1:
            req.fork_pending = [new_request(sampling.fork(i), parent=req)
                                for i in range(1, best_of)]
            req.fork_group = [req, *req.fork_pending]
        if not self.admitting:
            err = AdmissionRejected(
                f"request {req.request_id} rejected: engine is draining, "
                f"admissions are stopped", request_id=req.request_id,
                engine_id=self.engine_id)
            self._shed(req, err)
            raise err
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            # max_queue=0 is a legal admit-or-reject config: no queued
            # victim exists, so the newcomer is always the one rejected
            victim = min(self.queue,
                         key=lambda r: (r.priority, -r.request_id)) \
                if self.queue else None
            if victim is None or victim.priority >= req.priority:
                err = AdmissionRejected(
                    f"request {req.request_id} rejected: admission queue "
                    f"full ({self.max_queue}) and every queued request has "
                    f"priority >= {req.priority}", request_id=req.request_id,
                    engine_id=self.engine_id)
                self._shed(req, err)
                raise err
            self._shed(victim, AdmissionRejected(
                f"request {victim.request_id} (priority {victim.priority}) "
                f"shed from the full admission queue for higher-priority "
                f"request {req.request_id}", request_id=victim.request_id,
                engine_id=self.engine_id))
        self.queue.append(req)
        self._gauges()
        return req

    def step(self) -> bool:
        """One engine iteration: expire deadlines, admit, one batched decode
        step, prefill. Returns whether any scheduling progress was made
        (False = idle — and ``drain()`` treats a no-progress step with work
        remaining as a stall, not as quiet completion).

        Decode-first, chunked prefill interleaving: with a well-filled
        decode batch, prefill advances ONE chunk per iteration (a long
        prompt can only add one bounded chunk of latency between decode
        steps); with a thin batch, prefill bursts so arriving requests
        reach the decode batch quickly instead of trickling in one chunk
        per decode step."""
        self._step_count += 1
        busy = bool(self.queue) or self.active_requests > 0
        t0_us = _observe._now_us()
        worked = self._expire_deadlines()
        # pending best-of forks take slots before fresh admissions (they
        # are older traffic, and forking is cheaper than a prefill)
        for r in self.slots:
            if r is not None and r.fork_pending:
                worked = self._materialize_forks(r) or worked
        worked = self._admit() or worked
        if busy:
            # host-scheduling half of the iteration (deadlines + admission);
            # the dispatch halves record their own spans. Idle polling steps
            # stay out of the flight ring — a long idle stretch must not
            # flush the last incident's history out of the bounded ring.
            self.obs.record_span("schedule", "serving:sched", t0_us,
                                 _observe._now_us() - t0_us,
                                 {"step": self._step_count})
        worked = self._decode_step() or worked
        decoding = sum(1 for r in self.slots
                       if r is not None and r.state == DECODE)
        budget = 1 if decoding > self.max_slots // 2 else self.max_slots
        for _ in range(budget):
            if not self._prefill_one():
                break
            worked = True
            self._admit()  # a completed prefill may free queue back-pressure
        if busy or worked:
            # gauges are unchanged on a no-op idle step, and set_gauge
            # feeds the always-on flight ring — publishing them anyway
            # would let an idle polling loop flush the last incident's
            # history out of the bounded ring (same rule as the schedule
            # span above; every real transition path publishes its own)
            self._gauges()
        return worked

    def drain(self, max_steps: int = 1_000_000) -> list[Request]:
        """Run until every submitted request reaches a terminal state
        (completed or shed). Returns the completed requests in completion
        order. A step that makes NO progress (nothing admitted, prefilled,
        decoded, or shed) while requests remain raises
        ``EngineStallError`` naming the stuck requests — as does burning
        ``max_steps`` — instead of returning silently with work wedged."""
        for _ in range(max_steps):
            if self.idle:
                break
            if not self.step():
                raise self._stall_error("no-progress step")
        else:
            if not self.idle:
                raise self._stall_error(f"no completion in {max_steps} steps")
        return self.completed

    def stop_admissions(self) -> None:
        """Graceful-drain entry: every later ``submit()`` raises
        ``AdmissionRejected``; resident and queued requests keep running."""
        self.admitting = False

    def shed_outstanding(self, reason: str) -> list[Request]:
        """Shed every queued and resident request with ``DeadlineExceeded``
        (the graceful-drain wall-clock bound expired). Pages return to the
        free list; outputs produced so far stay readable on the request."""
        victims = list(self.queue) + [r for r in self.slots if r is not None]
        for req in victims:
            self._shed(req, DeadlineExceeded(
                f"request {req.request_id} shed: {reason}",
                request_id=req.request_id, engine_id=self.engine_id))
        return victims

    def rebuild_after_fault(self, restart_state: RestartState | None = None) \
            -> list[Request]:
        """Crash recovery (the supervisor's restart rung): discard the
        consumed device pools, build fresh ones, drop the stale decode
        binding, and re-queue every in-flight request for recompute-on-
        resume re-prefill — the same discipline as ``_preempt``, so
        surviving outputs stay token-identical to a fault-free run. The
        compiled prefill/decode programs survive (same shapes, same cache
        entries); only the pools and the binding are rebuilt.

        ``restart_state`` (the typed record the fault carried) must match
        this engine's own — the supervisor passes it back so a rebuild is
        provably SHARDING-identical, not just shape-identical; a mismatch
        is a lifecycle bug and raises ``ShardingGeometryError``."""
        if restart_state is not None \
                and restart_state != self._restart_state:
            raise ShardingGeometryError(
                "restart state mismatch: the fault's recorded pool spec "
                f"{restart_state.describe()} != this engine's "
                f"{self._restart_state.describe()}; rebuilding from it "
                "would not be sharding-identical")
        residents = sorted((r for r in self.slots if r is not None),
                           key=lambda r: r.admit_seq, reverse=True)
        for req in residents:
            self.slots[self.slots.index(req)] = None
            self._phase_end(req, reason="engine_restart")
            req.pages = []          # the pool they lived in is gone
            req.pages_version += 1
            req.prefilled = 0
            req.length = 0
            req.next_token = None
            req._replay = False
            req.state = QUEUED
            req.restarts += 1
            self.queue.appendleft(req)  # reverse admit order -> FIFO resume
            self._phase_begin(req, QUEUED)
        # rebuild from the typed restart state — geometry, dtype, AND mesh —
        # so a tensor-parallel engine's fresh pools come back committed to
        # the same NamedShardings the compiled SPMD step was built around
        # (geometry alone would rebuild an unsharded pool and the next
        # dispatch would recompile or crash)
        rs = self._restart_state
        self.cache = PagedKVCache(rs.geometry, rs.dtype, sharding=rs.mesh)
        if self.mesh is not None:
            from thunder_tpu.distributed.gspmd import mesh_descriptor

            self.obs.event("serving_mesh", phase="rebuild",
                           **mesh_descriptor(self.mesh))
        if self.prefix is not None:
            # the trie's pages died with the consumed pools: start a fresh
            # cache attached to the rebuilt allocator (re-donation refills
            # it as recovered requests complete)
            self.prefix = PrefixCache(self.cache)
        self._decode_bound = None
        self._bound_epoch = -1
        self._np_bt[:] = 0
        self._bt_slot_version = [None] * self.max_slots
        self._gauges()
        return residents

    def assert_quiescent(self) -> None:
        """Leak audit: the engine must be idle with every KV page back on
        the free list and every block-table row pointing only at the
        scratch page (see ``PagedKVCache.assert_quiescent``)."""
        busy = [r.request_id for r in self.slots if r is not None]
        if busy or self.queue:
            raise AssertionError(
                f"engine not idle: resident {busy}, "
                f"queued {[r.request_id for r in self.queue]}")
        self.cache.assert_quiescent(self._np_bt)

    def reset_slo_window(self) -> None:
        """Restart SLO-attainment accounting (benchmarks: exclude warmup)."""
        self._slo_attained = 0
        self._slo_total = 0
        self._slo_resets += 1

    @property
    def active_requests(self) -> int:
        return sum(s is not None for s in self.slots)

    @property
    def idle(self) -> bool:
        return not self.queue and not any(s is not None for s in self.slots)

    def describe_state(self) -> dict:
        """Plain-dict engine/cache state summary — what a postmortem bundle
        embeds: slot occupancy, queue, page accounting, block-table
        liveness, and the ``assert_quiescent`` findings (the finding TEXT
        when not quiescent — during a fault that is the interesting part)."""
        try:
            self.assert_quiescent()
            quiescence = "quiescent"
        except AssertionError as e:
            quiescence = str(e)
        return {
            "engine_id": self.engine_id,
            "step": self._step_count,
            "admitting": self.admitting,
            "slots": [{"slot": i, "request": r.request_id, "state": r.state,
                       "pages": len(r.pages), "prefilled": r.prefilled,
                       "length": r.length, "generated": len(r.generated),
                       "priority": r.priority, "preemptions": r.preemptions,
                       "restarts": r.restarts}
                      for i, r in enumerate(self.slots) if r is not None],
            "queued": [r.request_id for r in self.queue],
            "completed": len(self.completed),
            "shed": len(self.shed),
            "pages_free": self.cache.pages_free,
            "pages_total": self.cache.pages_total,
            "peak_pages_used": self.cache.peak_pages_used,
            "pools_alive": self.cache.pools_alive(),
            "cached_pages": self.cache.cached_pages,
            "cow_copies": self.cache.cow_copies,
            "prefix_hit_rate": (round(self.prefix.hit_rate(), 4)
                                if self.prefix is not None else None),
            "block_table_rows_live": int((self._np_bt != 0).any(1).sum()),
            "quiescence": quiescence,
            "slo": {"attained": self._slo_attained, "total": self._slo_total},
            "mesh": self._restart_state.describe(),
        }

    # -- scheduling internals -----------------------------------------------
    def _phase_begin(self, req: Request, phase: str) -> None:
        req._phase = phase
        req._phase_t0_us = _observe._now_us()

    def _phase_end(self, req: Request, **args) -> None:
        """Close the request's open lifecycle phase as a span on its
        Perfetto track (queued / prefill / decode; always in the flight
        ring). Queued time accumulates on the request for the timeline
        report and the bench's queue-time percentiles."""
        if not req._phase:
            return
        dur_us = _observe._now_us() - req._phase_t0_us
        if req._phase == QUEUED:
            req.queued_ms += dur_us / 1e3
        self.obs.record_span(req._phase, "serving:request", req._phase_t0_us,
                             dur_us, {"request": req.request_id, **args})
        req._phase = ""

    def _close_request_span(self, req: Request) -> None:
        """The terminal umbrella span: one bar covering submit -> terminal
        on the request's track, phases nested inside it."""
        self.obs.record_span(
            f"request {req.request_id}", "serving:request", req.submitted_us,
            _observe._now_us() - req.submitted_us,
            {"request": req.request_id, "state": req.state,
             "tokens": len(req.generated), "queued_ms": round(req.queued_ms, 3),
             "prefill_chunks": req.prefill_chunks,
             "preemptions": req.preemptions, "restarts": req.restarts})

    def _stall_error(self, why: str) -> EngineStallError:
        stuck = [(r.request_id, r.state) for r in self.queue]
        stuck += [(r.request_id, r.state)
                  for r in self.slots if r is not None]
        return EngineStallError(
            f"engine stalled ({why}) with {len(stuck)} request(s) "
            f"outstanding: {stuck} — free pages "
            f"{self.cache.pages_free}/{self.cache.pages_total}", stuck=stuck)

    def _gauges(self) -> None:
        self.obs.set_gauge("serving.queue_depth", len(self.queue))
        self.obs.set_gauge("serving.active_requests", self.active_requests)
        self.obs.set_gauge("serving.kv_pages_free", self.cache.pages_free)
        if self.prefix is not None:
            self.obs.set_gauge("serving.cached_pages", self.cache.cached_pages)
        if self._slo_total:
            self.obs.set_gauge("serving.slo_attainment",
                               self._slo_attained / self._slo_total)

    def _expire_deadlines(self) -> bool:
        """Shed expired queued requests and evict expired residents —
        deadline-aware scheduling's enforcement point, once per step."""
        now = time.perf_counter()
        expired = [r for r in self.queue
                   if r.deadline_at is not None and now > r.deadline_at]
        expired += [r for r in self.slots
                    if r is not None and r.deadline_at is not None
                    and now > r.deadline_at]
        # pending fork clones expire too (they ride a resident primary)
        expired += [c for r in self.slots if r is not None
                    for c in r.fork_pending
                    if c.deadline_at is not None and now > c.deadline_at]
        for req in expired:
            self._shed(req, DeadlineExceeded(
                f"request {req.request_id} missed its deadline "
                f"({req.deadline_at - req.submitted_s:.3f}s) in state "
                f"{req.state}", request_id=req.request_id,
                deadline_s=req.deadline_at - req.submitted_s,
                engine_id=self.engine_id))
        return bool(expired)

    def _shed(self, req: Request, error: BaseException) -> None:
        """Terminal removal with a typed error: from the queue, from a
        slot (pages freed through the refcount path, block-table row
        zeroed), from a primary's pending-fork list, or pre-admission.
        Pending clones die with their primary (they can't fork from a
        terminal request and were never independently queued)."""
        if req.state in (DONE, SHED):   # cascades can re-reach a terminal
            return
        shed_from = req.state           # the state it was shed FROM
        if req in self.queue:
            self.queue.remove(req)
        elif req in self.slots:
            self._release_slot(req)
        elif req.fork_parent is not None and \
                req in req.fork_parent.fork_pending:
            req.fork_parent.fork_pending.remove(req)
        for clone in list(req.fork_pending):
            kind = DeadlineExceeded if isinstance(error, DeadlineExceeded) \
                else AdmissionRejected
            self._shed(clone, kind(
                f"request {clone.request_id} shed with its fork primary "
                f"{req.request_id} ({type(error).__name__})",
                request_id=clone.request_id, engine_id=self.engine_id))
        req.fork_pending = []
        self._phase_end(req, reason=type(error).__name__)
        req.state = SHED
        req.error = error
        req.finished_s = time.perf_counter()
        self._close_request_span(req)
        self.shed.append(req)
        self._slo_total += 1
        self.obs.inc("serving.shed_requests")
        if isinstance(error, DeadlineExceeded):
            self.obs.inc("serving.deadline_misses")
        self.obs.event("serving_shed", request=req.request_id,
                       priority=req.priority, state=shed_from,
                       reason=type(error).__name__,
                       generated=len(req.generated))
        self._gauges()

    def _release_slot(self, req: Request) -> None:
        """Return a resident request's pages and zero its block-table row
        (the quiescence invariant: idle rows reference only page 0)."""
        slot = self.slots.index(req)
        self.cache.free(req.pages)
        req.pages = []
        req.pages_version += 1
        self.slots[slot] = None
        self._np_bt[slot] = 0
        self._bt_slot_version[slot] = None

    def _admit(self) -> bool:
        admitted = False
        while self.queue:
            slot = next((i for i, s in enumerate(self.slots) if s is None), None)
            if slot is None:
                break
            # priority-ordered admission: highest priority first, FIFO among
            # equals (all-default-priority traffic keeps the old strict FIFO)
            req = max(self.queue, key=lambda r: r.priority)
            wp = req.work_prompt
            # prefix-cache probe (sizing pass, nothing retained yet):
            # prefill starts at the first uncached page, so a hit shrinks
            # both the first chunk and the fresh-page demand
            hit = self.prefix.lookup(wp) if self.prefix is not None else []
            hit_tokens = len(hit) * self.geom.page_size
            first_chunk = self._chunk_size(len(wp) - hit_tokens)
            need_new = (hit_tokens + first_chunk) // self.geom.page_size \
                - len(hit)
            # availability check: hit pages parked at rc 0 are about to be
            # claimed, so they must not double-count as evictable headroom
            parked_hits = sum(1 for p in hit if self.cache.refcount(p) == 0)
            if self.cache.pages_free + self.cache.cached_pages \
                    - parked_hits < need_new:
                break   # page back-pressure: wait for completions/evictions
            try:
                _faults.maybe_fail("serving:admission", step=self._step_count)
            except _faults.InjectedFault as e:
                # contained: the request stays queued and this step's
                # admission round aborts; the next step retries it. The
                # deferral COUNTS as progress — drain() must read it as
                # "the engine deliberately waited", not as a stall (a
                # permanent admission fault still bounds out via max_steps)
                self.obs.event("serving_admission_fault", error=repr(e),
                               request=req.request_id)
                admitted = True
                break
            self.queue.remove(req)
            # commit: claim the probed chain FIRST (retained pages can't be
            # evicted out from under us by the alloc below), then the fresh
            # pages for the first uncached chunk
            chain = self.prefix.probe(wp, req.request_id, chain=hit) \
                if self.prefix is not None else []
            req.pages = chain + self.cache.alloc(need_new)
            req.pages_version += 1
            req.prefilled = len(chain) * self.geom.page_size
            req.prefix_hit_tokens = req.prefilled
            req.length = 0
            req.state = PREFILL
            req.admit_seq = next(self._admits)
            self.slots[slot] = req
            self._phase_end(req)            # close "queued"
            self.obs.event("serving_admitted", request=req.request_id,
                           slot=slot, preemptions=req.preemptions,
                           restarts=req.restarts,
                           prefix_hit_tokens=req.prefilled)
            self._phase_begin(req, PREFILL)
            admitted = True
        return admitted

    def _chunk_size(self, remaining: int) -> int:
        return self.max_chunk if remaining >= self.max_chunk \
            else self.chunker.bucket_for(remaining)

    def _padded_prefill_len(self, n: int) -> int:
        """Context length at the end of prefilling ``n`` tokens, including
        the final chunk's ladder padding."""
        full = (n // self.max_chunk) * self.max_chunk
        rem = n - full
        return full + (self.chunker.bucket_for(rem) if rem else 0)

    def _block_table(self, req: Request) -> np.ndarray:
        bt = np.zeros(self.geom.pages_per_request, np.int32)
        bt[:len(req.pages)] = req.pages
        return bt

    def _dispatch_guarded(self, dispatch, domain: str):
        """Run a pool-donating dispatch under retry. A retryable failure
        that consumed the donated pools mid-execution escalates FATAL (a
        blind re-run would crash on deleted buffers every attempt), and any
        failure that leaves the pools dead surfaces as ``EngineFault`` —
        the supervisor's restart signal."""
        def classify(exc):
            kind = _retry.classify(exc)
            if kind == _retry.RETRYABLE and not self.cache.pools_alive():
                return _retry.FATAL
            return kind

        try:
            return _retry.call_with_retry(dispatch, domain=domain,
                                          policy=self._retry_policy,
                                          classify_fn=classify)
        except (KeyboardInterrupt, SystemExit, GeneratorExit):
            raise
        except BaseException as e:
            if not self.cache.pools_alive():
                raise EngineFault(
                    f"{domain} dispatch consumed the donated page pools; "
                    f"in-place retry is impossible — supervisor restart "
                    f"(pool rebuild + re-prefill) required", domain=domain,
                    restart_state=self._restart_state,
                    engine_id=self.engine_id) from e
            raise

    def _prefill_one(self) -> bool:
        """Advance the head-of-line prefilling request by ONE chunk."""
        req = min((r for r in self.slots
                   if r is not None and r.state == PREFILL),
                  key=lambda r: r.admit_seq, default=None)
        if req is None:
            return False
        g = self.geom
        wp = req.work_prompt
        remaining = len(wp) - req.prefilled
        C = self._chunk_size(remaining)
        pos0 = req.prefilled                        # chunk/page aligned
        need_total = (pos0 + C) // g.page_size
        if len(req.pages) < need_total and \
                not self._grow_pages(req, need_total - len(req.pages)):
            return False                            # preempted or must wait
        real = min(remaining, C)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :real] = wp[pos0:pos0 + real]
        lengths = np.asarray([pos0 + C], np.int32)
        first_page = pos0 // g.page_size
        page_writes = np.asarray(
            [req.pages[first_page + i] * g.page_size for i in range(C // g.page_size)],
            np.int32)

        def dispatch():
            # the fault hook fires BEFORE the device dispatch, so a retried
            # injected fault re-runs on unconsumed inputs
            _faults.maybe_fail("serving:prefill", step=self._step_count)
            return self.runner.prefill_jit(
                self.params, chunk, self._block_table(req)[None], lengths,
                page_writes, self.cache.pools)

        t0 = time.perf_counter()
        t0_us = _observe._now_us()
        pools = self._dispatch_guarded(dispatch, "serving:prefill")
        self.cache.update_pools(pools)
        dur_us = _observe._now_us() - t0_us
        self.obs.observe_value("serving.prefill_ms",
                               (time.perf_counter() - t0) * 1e3)
        # the chunk dispatch on the request's own lifecycle track
        self.obs.record_span("prefill_chunk", "serving:request", t0_us, dur_us,
                             {"request": req.request_id, "chunk": C,
                              "pos0": pos0})
        req.prefill_chunks += 1
        self.obs.event("serving_prefill_chunk", request=req.request_id,
                       chunk=C, pos0=pos0, real=real)
        req.prefilled += real
        if req.prefilled == len(wp):                # prompt fully resident
            # no logits left prefill: the FIRST token comes from the next
            # batched decode step as a REPLAY — re-feed the last prompt
            # token (its K/V row already exists; the write goes to the
            # scratch page) and sample in-graph on the same program path
            # as every later token
            req.length = len(wp)
            req.next_token = int(wp[-1])
            req._replay = True
            req.state = DECODE
            self._phase_end(req)                    # close "prefill"
            self._phase_begin(req, DECODE)
            if req.decode_start_s is None:          # survive preempt-resume:
                # decode_ms stays first-token -> completion, as documented
                req.decode_start_s = time.perf_counter()
            if req.fork_pending:
                # the prompt is resident: best-of clones can fork it now
                self._materialize_forks(req)
        return True

    def _grow_pages(self, req: Request, n: int) -> bool:
        """Allocate ``n`` more pages for ``req``, preempting the lowest-
        priority newest resident request (possibly ``req`` itself) while
        the pool is dry."""
        while not self.cache.can_alloc(n):
            victim = min((r for r in self.slots
                          if r is not None and r.state in (DECODE, PREFILL)
                          and r is not req),
                         key=lambda r: (r.priority, -r.admit_seq),
                         default=None)
            if victim is None or victim.priority > req.priority:
                # nothing else to evict, or every other resident OUTRANKS
                # the grower ("higher survives longer" — evicting one would
                # be a priority inversion): requeue req itself and wait
                self._preempt(req)
                return False
            self._preempt(victim)
        req.pages.extend(self.cache.alloc(n))
        req.pages_version += 1
        return True

    def _preempt(self, req: Request) -> None:
        """Evict a resident request back to the queue head (recompute-on-
        resume). Its pages return to the free list immediately."""
        self._release_slot(req)
        self._phase_end(req, reason="preempt")
        req.prefilled = 0
        req.length = 0
        req.next_token = None
        req._replay = False
        req.state = QUEUED
        req.preemptions += 1
        self.queue.appendleft(req)
        self._phase_begin(req, QUEUED)
        self.obs.inc("serving.preempted_requests")
        self.obs.event("serving_preempt", request=req.request_id,
                       generated=len(req.generated))

    def _decode_step(self) -> bool:
        """One batched decode step over every resident DECODE request."""
        g = self.geom
        # page capacity first (may preempt, changing the active set)
        for req in list(self.slots):
            if req is None or req.state != DECODE:
                continue
            # a replay row writes nothing (scratch page): it only needs its
            # existing context pages, not the next append page yet
            need = (-(-req.length // g.page_size) if req._replay
                    else req.length // g.page_size + 1)
            if len(req.pages) < need:
                self._grow_pages(req, need - len(req.pages))
        active = [(i, r) for i, r in enumerate(self.slots)
                  if r is not None and r.state == DECODE]
        if not active:
            return False
        tokens, bt = self._np_tokens, self._np_bt
        lengths, write_pos = self._np_len, self._np_wp
        temps, topk = self._np_temp, self._np_topk
        topp, rng = self._np_topp, self._np_rng
        for i in range(self.max_slots):
            r = self.slots[i]
            if r is None or r.state != DECODE:
                # idle slots attend + scribble on the reserved page 0 only
                # (their block-table row is zeroed when the slot is
                # released, so the documented invariant holds exactly:
                # idle slots never read a live request's pages); their
                # sampling row is greedy on the zero key
                tokens[i, 0] = 0
                lengths[i] = 1
                write_pos[i] = 0
                temps[i] = 0.0
                topk[i] = 0
                topp[i] = 1.0
                rng[i] = 0
                if self._bt_slot_version[i] is not None:
                    bt[i] = 0
                    self._bt_slot_version[i] = None
        for i, r in active:
            tokens[i, 0] = r.next_token
            key = (r.request_id, r.pages_version)
            if self._bt_slot_version[i] != key:     # pages changed (rare)
                bt[i, :len(r.pages)] = r.pages
                bt[i, len(r.pages):] = 0
                self._bt_slot_version[i] = key
            if r._replay:
                # first-token replay: the fed token's K/V row already
                # exists at position length-1 (prefill wrote it, or the
                # fork copied it), so the context length is unchanged and
                # the recomputed row is discarded on the scratch page —
                # shared COW pages are never written
                lengths[i] = r.length
                write_pos[i] = 0
            else:
                lengths[i] = r.length + 1
                write_pos[i] = (r.pages[r.length // g.page_size] * g.page_size
                                + r.length % g.page_size)
            sp = r.sampling
            temps[i] = sp.temperature
            topk[i] = sp.top_k
            topp[i] = sp.top_p
            rng[i, 0] = r.stream_seed
            rng[i, 1] = len(r.generated)    # counter: tokens sampled so far

        def dispatch():
            # injected faults fire BEFORE the device dispatch, so a retried
            # transient re-runs on unconsumed inputs (`step` is the legacy
            # domain; `serving:decode` the serving-layer one)
            _faults.maybe_fail("step", step=self._step_count)
            _faults.maybe_fail("serving:decode", step=self._step_count)
            try:
                _faults.maybe_fail("serving:engine", step=self._step_count)
            except _faults.InjectedFault:
                # the engine domain simulates the REAL fatal failure mode —
                # a mid-execution accelerator fault that consumed the
                # donated page pools — so the supervisor's restart rung is
                # exercisable deterministically on CPU
                self.cache.consume_pools()
                raise
            # a quarantine containment inside a previous bound call
            # recompiled under a NEW cache entry (epoch bump); re-bind so
            # the fallback program serves — the stale bound entry would
            # re-enter containment (clear + recompile) on EVERY step
            ep = _quarantine.epoch()
            if self._decode_bound is None or self._bound_epoch != ep:
                if self._decode_bound is not None:
                    # the epoch MOVED under a live binding: a kernel was
                    # quarantined and the decode program is about to fall
                    # back (e.g. the decode-layer megakernel to its per-op
                    # form). Log it — a silent fallback would only show up
                    # as a throughput regression; the counter renders in
                    # explain()'s serving section, the event carries the
                    # epochs, and the rebind republishes the launch gauges.
                    self.decode_rebinds += 1
                    self.obs.inc("serving.decode_rebinds")
                    self.obs.event("serving_decode_rebind",
                                   old_epoch=self._bound_epoch, epoch=ep,
                                   quarantined=sorted(
                                       _quarantine.get_quarantine().ids()))
                self.obs.set_gauge("serving.quarantine_epoch", ep)
                self._decode_bound = self.runner.bind_decode(
                    self.params, tokens, bt, lengths, write_pos,
                    self.cache.pools, temps, topk, topp, rng)
                self._bound_epoch = ep
            return self._decode_bound(self.params, tokens, bt, lengths,
                                      write_pos, self.cache.pools,
                                      temps, topk, topp, rng)

        t0_us = _observe._now_us()
        tok_ids, _logits, pools = \
            self._dispatch_guarded(dispatch, "serving:decode")
        self.cache.update_pools(pools)
        # tokens were sampled IN-GRAPH; fetching the (S,) id vector is the
        # host sync that makes the span below an honest device-step bound
        # (the (S, V) logits output stays on device, unread)
        toks = np.asarray(tok_ids)
        # the dispatch half of the iteration, on the scheduler track
        self.obs.record_span("decode_dispatch", "serving:sched", t0_us,
                             _observe._now_us() - t0_us,
                             {"step": self._step_count, "batch": len(active)})
        for i, r in active:
            if r._replay:
                r._replay = False   # context length unchanged; row existed
            else:
                r.length += 1
            self._on_token(r, int(toks[i]))
        return True

    def _on_token(self, req: Request, tok: int) -> None:
        req.generated.append(tok)
        req.next_token = tok
        if req.ttft_s is None:
            req.ttft_s = time.perf_counter() - req.submitted_s
            self.obs.observe_value("serving.ttft_ms", req.ttft_s * 1e3)
            self.obs.event("serving_first_token", request=req.request_id,
                           ttft_ms=round(req.ttft_s * 1e3, 3))
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            self._finish(req)

    def _materialize_forks(self, primary: Request) -> bool:
        """Fork pending best-of clones off a resident primary whose prompt
        is fully resident: full prompt pages SHARED by refcount (zero bytes
        moved), only a partial tail page copied (``serving.cow_copies``).
        Each clone takes a free slot and enters decode in replay mode — its
        first token samples from the prompt's last-position logits on its
        own RNG stream, exactly like an independently-submitted request
        would. Clones that can't fork yet (no free slot, no page for the
        tail copy) stay pending and retry next step; the primary's terminal
        transition spills any remainder to the ordinary queue."""
        g = self.geom
        L = len(primary.prompt)
        n_ctx = g.pages_for(L)
        if primary.state != DECODE or len(primary.pages) < n_ctx:
            return False
        # priority-ordered slot acquisition applies to clones too: a
        # strictly higher-priority queued request gets the free slot (via
        # the admission pass that follows); equal priority favors the
        # clone — it is older traffic and forking is cheaper than prefill
        top_queued = max((r.priority for r in self.queue), default=None)
        worked = False
        while primary.fork_pending:
            if top_queued is not None and \
                    top_queued > primary.fork_pending[0].priority:
                break
            slot = next((i for i, s in enumerate(self.slots) if s is None),
                        None)
            if slot is None:
                break
            clone = primary.fork_pending[0]
            cow_before = self.cache.cow_copies
            try:
                pages = self.cache.fork(primary.pages, L)
            except OutOfPages:
                break       # tail copy can't allocate; retry under less load
            primary.fork_pending.pop(0)
            # the allocator owns the copy decision; read the count back
            # rather than re-deriving it (the two can't drift)
            copied = self.cache.cow_copies - cow_before
            if copied:
                self.obs.inc("serving.cow_copies", copied)
            clone.pages = pages
            clone.pages_version += 1
            clone.prefilled = L
            clone.length = L
            clone.next_token = int(clone.prompt[-1])
            clone._replay = True
            clone.state = DECODE
            clone.admit_seq = next(self._admits)
            self.slots[slot] = clone
            self._phase_end(clone)          # close "queued" (fork-pending)
            self.obs.event("serving_fork", request=clone.request_id,
                           parent=primary.request_id, slot=slot,
                           shared_pages=len(pages) - copied, copied=copied)
            self._phase_begin(clone, DECODE)
            if clone.decode_start_s is None:
                clone.decode_start_s = time.perf_counter()
            worked = True
        return worked

    def _finish(self, req: Request) -> None:
        if self.prefix is not None and req.pages:
            # donate the full prompt pages back BEFORE freeing: the
            # registration is what parks them (K/V preserved) when the
            # release below drops their last reference
            self.prefix.donate(req.work_prompt, req.pages)
        for clone in list(req.fork_pending):   # _shed mutates the list
            # never-forked clones fall back to the ordinary queue (full
            # prefill — which may now prefix-hit the donated prompt), but
            # the bounded-admission contract still applies: spill only up
            # to max_queue and shed the overflow typed, so best_of can't
            # grow the queue past the overload bound submit() enforces
            if self.max_queue is not None and \
                    len(self.queue) >= self.max_queue:
                self._shed(clone, AdmissionRejected(
                    f"request {clone.request_id} shed: fork primary "
                    f"{req.request_id} finished before the clone could "
                    f"fork and the admission queue is full "
                    f"({self.max_queue})", request_id=clone.request_id,
                    engine_id=self.engine_id))
            else:
                self.queue.appendleft(clone)
        req.fork_pending = []
        self._release_slot(req)
        self._phase_end(req)            # close "decode"
        req.state = DONE
        req.finished_s = time.perf_counter()
        self._close_request_span(req)
        if req.decode_start_s is not None:
            # per-request decode-phase duration (first token -> completion)
            self.obs.observe_value(
                "serving.decode_ms", (req.finished_s - req.decode_start_s) * 1e3)
        self.completed.append(req)
        self._slo_total += 1
        if req.deadline_at is None or req.finished_s <= req.deadline_at:
            self._slo_attained += 1
        else:
            # completed, but late: an SLO miss even though tokens shipped
            self.obs.inc("serving.deadline_misses")
        self.obs.event("serving_complete", request=req.request_id,
                       generated=len(req.generated),
                       preemptions=req.preemptions, restarts=req.restarts)

"""Cross-request prefix cache: a page-granularity token trie over the
paged KV pool.

The block-table indirection makes shared-prefix KV free in principle — a
page shared is a prefill skipped — and this module makes it free in
practice across REQUESTS: completed requests donate their full prompt
pages back keyed by token content, admission probes the trie with the new
prompt, and prefill starts at the first uncached page. For the
shared-system-prompt workload ("millions of users", ROADMAP 5(c)) a warm
cache collapses TTFT to one tail-chunk prefill.

Structure: one trie node per FULL page of prompt tokens, keyed by that
page's ``page_size`` token ids under its parent (so a node's path spells
the whole prefix — two prompts share a chain exactly as far as their
token ids agree on page boundaries). Each node owns one pool page whose
K/V holds those positions; positions are absolute from 0, and RoPE is
applied before K is written, so a cached page is valid for ANY request
whose prompt starts with the same tokens.

Lifecycle (see :class:`~thunder_tpu.serving.kv_cache.PagedKVCache`):

- **probe** walks the trie over the prompt's full pages (capped one short
  of the prompt so the tail always re-prefills and produces the rows the
  first decode step attends), retains every matched page into the
  request's block table, and returns the chain.
- **donate** registers a completed request's full prompt pages as trie
  nodes (first donor wins; identical-content duplicates from concurrent
  requests just stay unregistered and free normally). Registration parks
  the page in the allocator's *cached* set when its refcount drops —
  K/V preserved, evictable.
- **eviction** is driven by the ALLOCATOR, not the cache: when the free
  list runs dry, ``PagedKVCache.alloc`` reclaims parked pages oldest-
  first through :meth:`evict`, which drops the victim's trie node and its
  whole subtree (a live request using a descendant holds references on
  every ancestor, so an rc-0 page's subtree is rc-0 too). The cache can
  therefore never starve live traffic — ``OutOfPages`` only fires once
  the cache is empty.
"""

from __future__ import annotations

import hashlib

from thunder_tpu.observe import registry as _observe
from thunder_tpu.serving.kv_cache import PagedKVCache


def page_chunks(tokens, page_size: int) -> list[tuple]:
    """THE owner of the trie's content addressing: the per-page token-id
    tuples that key trie edges, capped at the last full page strictly
    before the final token (the lookup/donate cap — the tail always
    re-prefills, so it is never content-addressed). Both the trie walk
    and :func:`content_key` derive from this, so an external consumer of
    content keys (the fleet router's prefix affinity) can never drift
    from the keys the trie itself uses."""
    ps = page_size
    n_full = (len(tokens) - 1) // ps
    return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            for i in range(n_full)]


def content_key(tokens, page_size: int | None = None) -> str:
    """Stable content digest of a prompt prefix. With ``page_size``, the
    digest covers exactly the :func:`page_chunks` the trie would key —
    two prompts share a digest iff they would share a full trie chain.
    Without it, the digest covers the raw token ids (useful for whole-
    prompt identity). The fleet router hashes this to pin a shared
    prefix to one engine deterministically."""
    if page_size is not None:
        flat = [t for chunk in page_chunks(tokens, page_size)
                for t in chunk]
    else:
        flat = [int(t) for t in tokens]
    payload = ",".join(str(t) for t in flat).encode()
    return hashlib.blake2b(payload, digest_size=8).hexdigest()


class _Node:
    __slots__ = ("page", "parent", "chunk", "children")

    def __init__(self, page: int, parent, chunk: tuple):
        self.page = page
        self.parent = parent          # _Node | None (root children)
        self.chunk = chunk            # the page's token ids (trie edge key)
        self.children: dict[tuple, _Node] = {}


class PrefixCache:
    """Token-content trie mapping prompt prefixes to cached KV pages."""

    def __init__(self, cache: PagedKVCache):
        self.cache = cache
        self.page_size = cache.geometry.page_size
        self._root: dict[tuple, _Node] = {}
        self._by_page: dict[int, _Node] = {}
        # admission accounting for the serving.prefix_hit_rate gauge
        self.hit_tokens = 0
        self.probed_tokens = 0
        cache.evict_cb = self.evict

    # -- stats --------------------------------------------------------------
    @property
    def registered_pages(self) -> int:
        """Trie-held pages (live + parked) — the ``serving.cached_pages``
        gauge reads the parked count off the allocator; this is the trie's
        own footprint."""
        return len(self._by_page)

    def hit_rate(self) -> float:
        """Cumulative prompt-token hit ratio over every probe so far."""
        return self.hit_tokens / self.probed_tokens if self.probed_tokens \
            else 0.0

    # -- admission ----------------------------------------------------------
    def lookup(self, tokens) -> list[int]:
        """Longest cached page chain for ``tokens``, WITHOUT retaining —
        capped at the last full page strictly before the final token, so
        the request always prefills at least its tail (the rows the first
        decode step needs must exist, and a zero-work prefill has no
        program to run). Pair with :meth:`claim` once admission commits."""
        chain: list[int] = []
        level = self._root
        for key in page_chunks(tokens, self.page_size):
            node = level.get(key)
            if node is None:
                break
            chain.append(node.page)
            level = node.children
        return chain

    def claim(self, pages: list[int]) -> None:
        """Retain a probed chain into a request's block table (hit commit).
        Parked pages leave the evictable set while claimed; when the
        request later releases them they re-park at the LRU tail — so a
        hot prefix's recency refreshes through use, with no extra
        bookkeeping here."""
        self.cache.retain(pages)

    def probe(self, tokens, request_id=None, chain=None) -> list[int]:
        """Admission-path probe: look up, claim, count, and emit the
        ``serving_prefix_hit`` lifecycle event. Returns the retained page
        chain (possibly empty). Callers that already ran :meth:`lookup`
        for sizing pass the result back as ``chain`` — the commit then
        provably claims the same pages the sizing saw, with no second
        trie walk."""
        if chain is None:
            chain = self.lookup(tokens)
        self.probed_tokens += len(tokens)
        if chain:
            self.claim(chain)
            self.hit_tokens += len(chain) * self.page_size
            _observe.event("serving_prefix_hit", request=request_id,
                           pages=len(chain),
                           tokens=len(chain) * self.page_size,
                           prompt_tokens=len(tokens))
        _observe.set_gauge("serving.prefix_hit_rate", self.hit_rate())
        return chain

    # -- donation -----------------------------------------------------------
    def donate(self, tokens, pages: list[int]) -> int:
        """Register a completed request's full prompt pages as trie nodes.
        Call BEFORE freeing the request's pages: registration is what
        parks them (K/V preserved) when their refcount drops. Pages whose
        prefix is already cached (another donor got there first) are left
        unregistered — they free normally; the trie never holds two pages
        for one prefix. Returns the number of newly registered pages.

        Donation is capped at the last full page strictly before the
        FINAL token: the final token of a completed request never has a
        K/V row (it was sampled but never fed back — prefill writes
        positions < len(prompt), each decode step writes the PREVIOUS
        sample's row), so for a page-aligned ``tokens`` the last full
        page holds one unwritten row and caching it would hand garbage
        K/V to every future prefix hit. Symmetric with
        :meth:`lookup`'s cap."""
        chunks = page_chunks(tokens, self.page_size)[:len(pages)]
        level, parent, added = self._root, None, 0
        for i, key in enumerate(chunks):
            node = level.get(key)
            if node is None:
                node = _Node(pages[i], parent, key)
                level[key] = node
                self._by_page[node.page] = node
                self.cache.register_cached(node.page)
                added += 1
            elif node.page != pages[i]:
                # duplicate content under a different page: keep the
                # incumbent, stop descending — a child registered under
                # OUR page would be unreachable through the incumbent
                break
            level, parent = node.children, node
        return added

    def clear(self) -> None:
        """Drop the whole trie and un-register every page (parked pages
        return to the free list; live ones stop parking on release). Used
        by benchmarks to re-run the cold-cache scenario, and by the engine
        restart path when the pool the pages lived in is gone."""
        for page in list(self._by_page):
            self.cache.unregister_cached(page)
        self._root.clear()
        self._by_page.clear()
        self.hit_tokens = 0
        self.probed_tokens = 0

    # -- eviction (allocator pressure callback) -----------------------------
    def evict(self, page: int) -> list[int]:
        """Drop the trie node owning ``page`` plus its whole subtree and
        return every owned page for the allocator to reclaim. Only ever
        called by ``PagedKVCache.alloc`` on parked rc-0 pages; subtree
        pages are rc-0 by the ancestor-reference invariant."""
        node = self._by_page.get(page)
        if node is None:
            return [page]        # unregistered parked page (defensive)
        (node.parent.children if node.parent is not None
         else self._root).pop(node.chunk, None)
        dropped: list[int] = []
        stack = [node]
        while stack:
            n = stack.pop()
            self._by_page.pop(n.page, None)
            dropped.append(n.page)
            stack.extend(n.children.values())
        _observe.inc("serving.cache_evictions", len(dropped))
        _observe.event("serving_cache_evict", pages=dropped,
                       trigger_page=page)
        return dropped

"""Typed serving errors: the request-SLO and engine-lifecycle vocabulary.

Every failure a caller can act on gets its own type — catching broad
``RuntimeError`` around ``submit()``/``drain()`` cannot distinguish "your
request was load-shed, resubmit later" from "the engine is wedged, page
somebody". The hierarchy:

- :class:`ServingError` — base for everything below.
- :class:`AdmissionRejected` — the request never became (or stopped being)
  resident for capacity/lifecycle reasons: admissions stopped by a drain,
  the bounded admission queue shed it under priority pressure, or a
  graceful-drain wall-clock bound evicted it.
- :class:`InfeasibleRequest` — the request could NEVER run on this engine
  (context window or total page pool too small); raised at ``submit()``
  time so an impossible request fails fast instead of queueing forever and
  wedging ``drain()``. Subclasses ``ValueError`` too: infeasibility is a
  caller bug, and pre-SLO code that caught ``ValueError`` keeps working.
- :class:`DeadlineExceeded` — the request's SLO deadline passed before it
  completed (shed from the queue, evicted mid-flight, or drained past the
  bound).
- :class:`EngineFault` — the engine's device state is unrecoverable in
  place (a failing dispatch consumed the donated page pools): a blind
  retry would crash on deleted buffers, so the engine escalates this to
  its supervisor, whose restart (pool rebuild + re-prefill of every
  in-flight request) is the only recovery rung.
- :class:`EngineStallError` — a ``drain()`` step made no progress (nothing
  admitted, prefilled, decoded, or shed) while requests remain; names the
  stuck requests instead of burning ``max_steps`` silently.
- :class:`RestartBudgetExceeded` — the supervisor's sliding-window restart
  budget ran out; the engine is failing faster than restarts can honestly
  mask, so the failure escalates to the caller. The health plane
  (:mod:`thunder_tpu.serving.health`) reads the same budget: a refused
  restart is what flips an engine's health to its terminal ``DEAD`` state,
  and each masked ``EngineFault`` restart reads as a ``DEGRADED`` episode.
- :class:`ShardingGeometryError` — the paged-pool geometry cannot be
  sharded over the requested mesh (kv-head count not divisible by the
  mesh axis size); raised at pool-construction time so a bad split fails
  typed instead of as an opaque XLA partitioner error. Subclasses
  ``ValueError`` too: it is a configuration bug.

Every per-engine error above (admission, deadline, fault, restart budget)
also carries ``engine_id`` so a fleet-level caller — the router, a
postmortem bundle — can attribute the failure to the engine that raised
it without string-parsing the message. ``engine_id`` is ``None`` when the
rejection happened above any single engine (e.g. the router's own
fleet-edge admission queue).

:class:`RestartState` is not an error: it is the typed record of what a
post-crash rebuild must reproduce — pool geometry, dtype, AND the mesh /
sharding plan — carried on :class:`EngineFault` so the supervisor's
restart is sharding-identical, not just shape-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True)
class RestartState:
    """Everything a rebuild-after-crash needs to recreate the KV pool
    exactly: geometry + dtype (shape identity) and the tensor-parallel
    mesh (sharding identity — ``None`` for single-device engines)."""

    geometry: Any
    dtype: Any
    mesh: Any = None

    def describe(self) -> dict:
        d = {"n_layers": self.geometry.n_layers,
             "kv_heads": self.geometry.kv_heads,
             "num_pages": self.geometry.num_pages,
             "tp_degree": 1, "mesh_shape": [1]}
        if self.mesh is not None:
            md = self.mesh.describe()
            d["tp_degree"] = int(md["tp"])
            d["mesh_shape"] = list(md["mesh_shape"])
        return d


class ServingError(RuntimeError):
    """Base class for typed serving-engine errors."""


class AdmissionRejected(ServingError):
    """The engine refused (or revoked) admission for capacity/lifecycle
    reasons — draining, a full bounded queue, or priority shedding."""

    def __init__(self, message: str, *, request_id: int | None = None,
                 engine_id: str | None = None):
        super().__init__(message)
        self.request_id = request_id
        self.engine_id = engine_id


class InfeasibleRequest(AdmissionRejected, ValueError):
    """The request can never run on this engine (context window or total
    KV page pool too small) — raised at ``submit()`` so it fails fast."""


class DeadlineExceeded(ServingError):
    """The request's SLO deadline passed before completion."""

    def __init__(self, message: str, *, request_id: int | None = None,
                 deadline_s: float | None = None,
                 engine_id: str | None = None):
        super().__init__(message)
        self.request_id = request_id
        self.deadline_s = deadline_s
        self.engine_id = engine_id


class EngineFault(ServingError):
    """Device state lost mid-dispatch (donated page pools consumed by a
    failing step): per-step retry is impossible; only a supervised engine
    restart — pool rebuild plus re-prefill of in-flight requests — can
    recover. Carries the dispatch ``domain`` that escalated."""

    def __init__(self, message: str, *, domain: str = "",
                 restart_state: RestartState | None = None,
                 engine_id: str | None = None):
        super().__init__(message)
        self.domain = domain
        self.restart_state = restart_state
        self.engine_id = engine_id


class EngineStallError(ServingError):
    """``drain()`` detected a step with no progress while requests remain.
    ``stuck`` holds ``(request_id, state)`` pairs for triage."""

    def __init__(self, message: str, *, stuck: list | None = None):
        super().__init__(message)
        self.stuck = list(stuck or [])


class RestartBudgetExceeded(ServingError):
    """The supervisor's sliding-window restart budget is exhausted."""

    def __init__(self, message: str, *, in_window: int = 0,
                 max_restarts: int = 0, engine_id: str | None = None):
        super().__init__(message)
        self.in_window = in_window
        self.max_restarts = max_restarts
        self.engine_id = engine_id


class ShardingGeometryError(ServingError, ValueError):
    """The paged-pool geometry cannot be split over the mesh: the kv-head
    count must be divisible by the tensor-parallel axis size."""

    def __init__(self, message: str, *, kv_heads: int = 0, tp: int = 0):
        super().__init__(message)
        self.kv_heads = kv_heads
        self.tp = tp

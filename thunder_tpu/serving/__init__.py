"""Production serving runtime: continuous batching over a paged KV cache,
supervised for survival under fire.

The layers (ROADMAP item 1 + the serving containment story):

- :mod:`thunder_tpu.serving.kv_cache` — block-allocated page pool +
  free-list + per-request block tables (requests at any mix of sequence
  lengths share one device allocation, one compiled decode shape), with
  per-page REFCOUNTS (copy-on-write ``fork`` shares full pages, copies
  only the partial tail) and the refcount-aware
  :meth:`~kv_cache.PagedKVCache.assert_quiescent` leak audit.
- :mod:`thunder_tpu.serving.sampling` — in-graph sampling:
  :class:`~sampling.SamplingParams` per request, sort-free top-k/top-p
  threshold masking + Gumbel-max draw fused into the decode program
  (greedy == ``temperature 0``; the scheduler reads tokens, not logits).
- :mod:`thunder_tpu.serving.prefix_cache` — cross-request prefix cache: a
  page-granularity token trie; admission probes it, completed requests
  donate their prompt pages, the allocator evicts parked pages under
  pressure (the cache can never starve live traffic).
- :mod:`thunder_tpu.serving.runner` — the compiled paged prefill/decode
  step programs (``bind()``-dispatched decode; ``LengthBucketer``-laddered
  prefill chunks; ragged attention via ``nn.paged_decode_attention``,
  Pallas-claimed on TPU; sampling as the decode epilogue — prefill carries
  no lm_head, first tokens ride a decode replay step).
- :mod:`thunder_tpu.serving.scheduler` — admission (priority-ordered,
  optionally bounded, infeasibility-checked), deadline-aware continuous
  batching with chunked prefill interleaving, mid-flight join/evict,
  page-pressure preemption, load shedding with typed errors
  (:mod:`~thunder_tpu.serving.errors`), ``serving:*``-domain retry, and
  the ``serving.*`` observe metrics.
- :mod:`thunder_tpu.serving.supervisor` — the engine-level fallback rung:
  crash recovery (pool rebuild + re-prefill of in-flight requests, charged
  to a sliding-window :class:`~thunder_tpu.runtime.retry.RestartBudget`),
  graceful ``drain()``/``shutdown()``, and a heartbeat watchdog.
- :mod:`thunder_tpu.serving.health` — the fleet plane: every engine's
  telemetry is labeled with its process-unique ``engine_id``;
  :class:`~health.EngineHealth` scores it into a typed
  HEALTHY/DEGRADED/DRAINING/DEAD machine with hysteresis, and a
  :class:`~health.FleetObservatory` aggregates N supervised engines
  (fleet SLO, merged explain section, cross-engine postmortems, statusz
  directory aggregation).
- :mod:`thunder_tpu.serving.router` — one ``submit()``/``step()`` surface
  over N supervised engines: health-gated, cache-affine, least-loaded
  placement through a composable policy chain
  (:class:`~router.FleetRouter`), a decision log for every placement,
  failover re-admission of in-flight requests off dead engines
  (token-identical, recompute-on-resume), and drain-time
  :meth:`~router.FleetRouter.rebalance`.

>>> from thunder_tpu.serving import EngineSupervisor, ServingEngine
>>> eng = ServingEngine(params, cfg, max_slots=8, page_size=16,
...                     max_context=256, n_layers=2)
>>> sup = EngineSupervisor(eng, max_restarts=3, restart_window_s=600.0)
>>> req = sup.submit(prompt_ids, max_new_tokens=32, deadline_s=30.0)
>>> sup.drain(); req.output()

``bench_serve.py`` at the repo root is the committed throughput benchmark
(requests/s and aggregate decode tokens/s at a latency SLO; ``--overload``
measures shedding and SLO attainment past capacity).
"""

from thunder_tpu.serving.events import EVENT_KINDS  # noqa: F401
from thunder_tpu.serving.errors import (  # noqa: F401
    AdmissionRejected,
    DeadlineExceeded,
    EngineFault,
    EngineStallError,
    InfeasibleRequest,
    RestartBudgetExceeded,
    RestartState,
    ServingError,
    ShardingGeometryError,
)
from thunder_tpu.serving.health import (  # noqa: F401
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTH_STATES,
    HEALTHY,
    EngineHealth,
    FleetObservatory,
    HealthPolicy,
)
from thunder_tpu.serving.kv_cache import (  # noqa: F401
    OutOfPages,
    PagedKVCache,
    PageGeometry,
)
from thunder_tpu.serving.prefix_cache import (  # noqa: F401
    PrefixCache,
    content_key,
)
from thunder_tpu.serving.router import (  # noqa: F401
    FleetRouter,
    HealthGate,
    LeastLoaded,
    PrefixAffinity,
    RandomPlacement,
    RoutingPolicy,
)
from thunder_tpu.serving.runner import PagedLlamaRunner  # noqa: F401
from thunder_tpu.serving.sampling import (  # noqa: F401
    GREEDY,
    SamplingParams,
    sample_tokens,
)
from thunder_tpu.serving.scheduler import Request, ServingEngine  # noqa: F401
from thunder_tpu.serving.supervisor import EngineSupervisor  # noqa: F401

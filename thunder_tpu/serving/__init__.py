"""Production serving runtime: continuous batching over a paged KV cache.

The three layers (ROADMAP item 1):

- :mod:`thunder_tpu.serving.kv_cache` — block-allocated page pool +
  free-list + per-request block tables (requests at any mix of sequence
  lengths share one device allocation, one compiled decode shape).
- :mod:`thunder_tpu.serving.runner` — the compiled paged prefill/decode
  step programs (``bind()``-dispatched decode; ``LengthBucketer``-laddered
  prefill chunks; ragged attention via ``nn.paged_decode_attention``,
  Pallas-claimed on TPU).
- :mod:`thunder_tpu.serving.scheduler` — admission, decode-first
  continuous batching with chunked prefill interleaving, mid-flight
  join/evict, page-pressure preemption, ``step``-domain retry, and the
  ``serving.*`` observe metrics.

>>> from thunder_tpu.serving import ServingEngine
>>> eng = ServingEngine(params, cfg, max_slots=8, page_size=16,
...                     max_context=256, n_layers=2)
>>> req = eng.submit(prompt_ids, max_new_tokens=32)
>>> eng.drain(); req.output()

``bench_serve.py`` at the repo root is the committed throughput benchmark
(requests/s and aggregate decode tokens/s at a latency SLO).
"""

from thunder_tpu.serving.kv_cache import (  # noqa: F401
    OutOfPages,
    PagedKVCache,
    PageGeometry,
)
from thunder_tpu.serving.runner import PagedLlamaRunner  # noqa: F401
from thunder_tpu.serving.scheduler import Request, ServingEngine  # noqa: F401

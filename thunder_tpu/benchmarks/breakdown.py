"""Per-component timing attribution for the bench step (verdict r3 #2).

The reference harness times per-region kernels inside a step
(``thunder/benchmarks/__init__.py:241-460``, pre/post-region hooks). A
tunneled TPU exposes no per-kernel profile, so attribution here is by
**program knockout**: time nested sub-programs of the train step —

    fwd                  (loss only)
    fwd+bwd              (value_and_grad, no optimizer)
    full                 (fwd+bwd+AdamW)
    attention fwd+bwd    (isolated at the bench shape, x n_layers)
    lm_head + CE fwd+bwd (isolated at the bench shape)

— and report the differences: bwd = (fwd+bwd) - fwd, optimizer = full -
(fwd+bwd), "everything else" (linears/norms/rope/embed) = (fwd+bwd) -
attention - CE. Differences of medians on a shared chip carry ~±10% noise;
they answer "which component eats the gap to peak", which is the question
the round needed answered (not ns-exact kernel times).

Run: BENCH_BREAKDOWN=1 python bench.py   (writes BENCH_BREAKDOWN.json)
"""

from __future__ import annotations

import json
import sys
import time


def _force(x):
    import jax.numpy as jnp
    import jax

    leaves = [l for l in jax.tree_util.tree_leaves(x) if hasattr(l, "shape")]
    return float(jnp.sum(leaves[0].astype(jnp.float32))) if leaves else None


def time_fn(fn, *args, steps: int = 5, trials: int = 3) -> float:
    """Best-of-trials mean seconds per call (compile excluded)."""
    out = fn(*args)
    _force(out)
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        _force(out)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def run_breakdown(*, cfg, n_layers, params, tokens, targets,
                  model_loss, t_full: float, steps: int, opt=None) -> dict:
    import jax
    import numpy as np

    import thunder_tpu as tt
    from thunder_tpu import ops
    from thunder_tpu.ops import nn as ops_nn

    B, T = tokens.shape
    # inputs for the ISOLATED sub-programs live on device up front: at the
    # bench shape q/k/v and the (B·T, dim) hidden are ~256 MB each — feeding
    # them as host numpy would re-ship them through the (tunneled) PCIe/grpc
    # path every call and the transfer, not the kernel, would be measured
    # (r4's toy-scale run hid this; the r5 chip run surfaced 36 s/call)
    params = jax.device_put(params)

    # fwd only
    jfwd = tt.jit(lambda p: model_loss(p, tokens, targets, cfg))
    t_fwd = time_fn(jfwd, params, steps=steps)

    # fwd + bwd (no optimizer)
    jfb = tt.jit(lambda p: tt.value_and_grad(
        lambda q: model_loss(q, tokens, targets, cfg))(p))
    t_fb = time_fn(jfb, params, steps=steps)

    # attention alone at the bench shape (per layer), fwd+bwd
    hd = cfg.head_dim
    rng = np.random.RandomState(0)
    q = jax.device_put((rng.randn(B, cfg.n_heads, T, hd).astype(np.float32) * 0.1)
                       .astype(cfg.dtype.jax))
    k = q  # read-only inputs (no donation): one device buffer serves all three
    v = q

    def att_loss(qkv):
        qq, kk, vv = qkv
        return ops.sum(ops_nn.scaled_dot_product_attention(qq, kk, vv, is_causal=True))

    jatt = tt.jit(lambda qkv: tt.value_and_grad(att_loss)(qkv))
    t_att1 = time_fn(jatt, (q, k, v), steps=steps)

    # lm_head matmul + CE at the bench shape, fwd+bwd
    h = jax.device_put((rng.randn(B * T, cfg.dim).astype(np.float32) * 0.1)
                       .astype(cfg.dtype.jax))
    w = params["lm_head"]
    tg = jax.device_put(targets.reshape(-1))

    def ce_loss(args):
        hh, ww = args
        out = ops_nn.fused_linear_cross_entropy(hh, ww, tg)
        return out[0] if isinstance(out, tuple) else out

    jce = tt.jit(lambda a: tt.value_and_grad(ce_loss)(a))
    t_ce = time_fn(jce, (h, w), steps=steps)

    # MLP sub-block fwd+bwd at the bench shape (per layer, x n_layers),
    # compiled with the block planner FORCED on so the chain runs as the
    # claimed nn.mlp_subblock megakernel — the isolated number the Fusion 3.0
    # planner is accountable to against the linears_norms_rest residual
    # (PERF_R7). block_fusion=True (not the cost-model default) because this
    # row measures the planned kernel, not the planning decision.
    layer0 = params["layers"][0]
    hres = jax.device_put((rng.randn(B, T, cfg.dim).astype(np.float32) * 0.1)
                          .astype(cfg.dtype.jax))
    xattn = jax.device_put((rng.randn(B, T, cfg.dim).astype(np.float32) * 0.1)
                           .astype(cfg.dtype.jax))
    sub_w = jax.device_put({k: layer0[k] for k in
                            ("mlp_norm", "w_gate", "w_up", "w_down")})

    def sub_loss(args):
        hh, xx, w = args
        h2 = ops.add(hh, xx)
        n = ops.rms_norm(h2, w["mlp_norm"], eps=cfg.norm_eps)
        gate = ops.silu(ops.linear(n, w["w_gate"]))
        up = ops.linear(n, w["w_up"])
        out = ops.add(h2, ops.linear(ops.mul(gate, up), w["w_down"]))
        return ops.sum(out)

    jsub = tt.jit(lambda a: tt.value_and_grad(sub_loss)(a), block_fusion=True)
    t_sub = time_fn(jsub, (hres, xattn, sub_w), steps=steps) * n_layers

    t_att = t_att1 * n_layers
    t_bwd = max(0.0, t_fb - t_fwd)
    t_opt = max(0.0, t_full - t_fb)
    t_rest = max(0.0, t_fb - t_att - t_ce)

    rows = {
        "full_step_ms": t_full * 1e3,
        "forward_ms": t_fwd * 1e3,
        "backward_ms(delta)": t_bwd * 1e3,
        "optimizer_ms(delta)": t_opt * 1e3,
        "attention_fwdbwd_ms(isolated x layers)": t_att * 1e3,
        "lmhead_ce_fwdbwd_ms(isolated)": t_ce * 1e3,
        "linears_norms_rest_ms(residual)": t_rest * 1e3,
        # planned MLP sub-block megakernel, fwd+bwd, x n_layers — compare
        # against linears_norms_rest_ms: the planner's target chain
        "subblock_fused_ms(isolated)": t_sub * 1e3,
    }

    # isolated optimizer update fed by REAL gradients: the knockout delta
    # above includes XLA's cross-phase scheduling interplay — this is the
    # kernel-only number the fused multi-tensor optimizer (PERF_R6) is
    # measured against. No donation: time_fn re-feeds the same buffers each
    # trial, and donated inputs are consumed on first use.
    if opt is not None:
        _, grads = jfb(params)
        opt_state = jax.device_put(opt.init(params))
        jupd = tt.jit(lambda p, g, s: opt.update(p, g, s))
        rows["adamw_update_ms(isolated)"] = time_fn(
            jupd, params, grads, opt_state, steps=steps) * 1e3
    print("--- breakdown (knockout attribution, ±10% shared-chip noise) ---",
          file=sys.stderr)
    for k_, v_ in rows.items():
        share = v_ / (t_full * 1e3) * 100.0
        print(f"{k_:45s} {v_:8.1f} ms  {share:5.1f}% of step", file=sys.stderr)
    return rows


def save(rows: dict, meta: dict, path: str = "BENCH_BREAKDOWN.json") -> None:
    with open(path, "w") as f:
        json.dump({"meta": meta, "rows": rows}, f, indent=1)

"""Per-kernel microbenchmark table: each Pallas kernel vs its XLA lowering.

VERDICT r1 item 5: "a committed per-kernel table showing each Pallas kernel
beats its XLA lowering (else the kernel shouldn't claim)". Run on a real TPU:

    python -m thunder_tpu.benchmarks.kernel_table          # prints markdown
    python -m thunder_tpu.benchmarks.kernel_table --json   # JSON lines

Workloads mirror the claim surface: SDPA fwd and fwd+bwd (flash streaming
kernels vs XLA softmax-matmul), fused cross-entropy rows, fused RMSNorm.
Timing is min-of-trials with host-readback sync (block_until_ready is
unreliable through the axon tunnel).
"""

from __future__ import annotations

import json
import math
import sys
import time

import numpy as np


def _sync(out):
    import jax.numpy as jnp

    return np.asarray(jnp.ravel(out[0] if isinstance(out, (tuple, list)) else out)[0])


def _time_pair(fa, fb, args, rounds=8, iters=20):
    """Interleaved A/B timing: the shared tunneled chip drifts by tens of
    percent between back-to-back runs, so alternating the two sides each
    round cancels the drift; min-of-rounds is the device capability."""
    ta, tb = [], [float("inf")]
    _sync(fa(*args))
    if fb is not None:
        _sync(fb(*args))
        tb = []
    for _r in range(rounds):
        t0 = time.perf_counter()
        for _i in range(iters):
            out = fa(*args)
        _sync(out)
        ta.append((time.perf_counter() - t0) / iters)
        if fb is not None:
            t0 = time.perf_counter()
            for _i in range(iters):
                out = fb(*args)
            _sync(out)
            tb.append((time.perf_counter() - t0) / iters)
    return min(ta), min(tb)


def run_table():
    import jax
    import jax.numpy as jnp

    from thunder_tpu.executors.pallasex import (
        pallas_ce_fwd, pallas_rms_norm, pallas_sdpa_bwd, pallas_sdpa_fwd,
    )

    rows = []

    def xla_sdpa(q, k, v):
        hd = q.shape[-1]
        T = q.shape[-2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / math.sqrt(hd)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    # -- SDPA forward --------------------------------------------------------
    for (B, H, T, hd) in [(8, 32, 2048, 128), (1, 8, 8192, 128)]:
        mk = lambda s: jax.random.normal(jax.random.PRNGKey(s), (B, H, T, hd), jnp.bfloat16)
        q, k, v = mk(0), mk(1), mk(2)
        fp = jax.jit(lambda q, k, v: pallas_sdpa_fwd(q, k, v, True)[0])
        fx = jax.jit(xla_sdpa)
        try:
            tp, tx = _time_pair(fp, fx, (q, k, v))
        except Exception:
            tp, tx = _time_pair(fp, None, (q, k, v))
        rows.append({"kernel": "sdpa_fwd", "shape": f"({B},{H},{T},{hd}) bf16 causal",
                     "pallas_ms": round(tp * 1e3, 2),
                     "xla_ms": round(tx * 1e3, 2) if tx != float("inf") else None,
                     "speedup": round(tx / tp, 2) if tx != float("inf") else None})

    # -- SDPA fwd+bwd --------------------------------------------------------
    for (B, H, T, hd) in [(8, 32, 2048, 128)]:
        mk = lambda s: jax.random.normal(jax.random.PRNGKey(s), (B, H, T, hd), jnp.bfloat16)
        q, k, v, g = mk(0), mk(1), mk(2), mk(3)
        fp = jax.jit(lambda q, k, v, g: pallas_sdpa_bwd(
            g, q, k, v, *pallas_sdpa_fwd(q, k, v, True), True))

        def xla_fwd_bwd(q, k, v, g):
            def loss(q, k, v):
                return (xla_sdpa(q, k, v).astype(jnp.float32) * g.astype(jnp.float32)).sum()

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        fx = jax.jit(xla_fwd_bwd)
        try:
            tp, tx = _time_pair(fp, fx, (q, k, v, g))
        except Exception:
            tp, tx = _time_pair(fp, None, (q, k, v, g))
        rows.append({"kernel": "sdpa_fwd+bwd", "shape": f"({B},{H},{T},{hd}) bf16 causal",
                     "pallas_ms": round(tp * 1e3, 2),
                     "xla_ms": round(tx * 1e3, 2) if tx != float("inf") else None,
                     "speedup": round(tx / tp, 2) if tx != float("inf") else None})

    # -- fused cross-entropy -------------------------------------------------
    for (N, V) in [(16384, 32000)]:
        logits = jax.random.normal(jax.random.PRNGKey(0), (N, V), jnp.bfloat16)
        tgt = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V, jnp.int32)
        fp = jax.jit(lambda l, t: pallas_ce_fwd(l, t)[0])

        def xla_ce(l, t):
            lf = l.astype(jnp.float32)
            lse = jax.nn.logsumexp(lf, axis=-1)
            picked = jnp.take_along_axis(lf, t[:, None], 1)[:, 0]
            return lse - picked

        fx = jax.jit(xla_ce)
        tp, tx = _time_pair(fp, fx, (logits, tgt))
        rows.append({"kernel": "ce_fwd", "shape": f"({N},{V}) bf16",
                     "pallas_ms": round(tp * 1e3, 2), "xla_ms": round(tx * 1e3, 2),
                     "speedup": round(tx / tp, 2)})

    # -- fused rms_norm ------------------------------------------------------
    for (N, D) in [(16384, 4096)]:
        x = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (D,), jnp.bfloat16)
        fp = jax.jit(lambda x, w: pallas_rms_norm(x, w))

        def xla_rms(x, w):
            xf = x.astype(jnp.float32)
            ms = jnp.mean(xf * xf, -1, keepdims=True)
            return (xf * jax.lax.rsqrt(ms + 1e-5)).astype(x.dtype) * w

        fx = jax.jit(xla_rms)
        tp, tx = _time_pair(fp, fx, (x, w))
        rows.append({"kernel": "rms_norm", "shape": f"({N},{D}) bf16",
                     "pallas_ms": round(tp * 1e3, 2), "xla_ms": round(tx * 1e3, 2),
                     "speedup": round(tx / tp, 2)})

    return rows


def main():
    import jax

    rows = run_table()
    if "--json" in sys.argv:
        for r in rows:
            print(json.dumps(r))
        return
    print(f"# Pallas kernels vs XLA lowering ({jax.devices()[0].device_kind})\n")
    print("| kernel | shape | pallas ms | xla ms | speedup |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['kernel']} | {r['shape']} | {r['pallas_ms']} | "
              f"{r['xla_ms']} | {r['speedup']}x |")


if __name__ == "__main__":
    main()

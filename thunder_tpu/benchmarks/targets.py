"""pytest benchmark grid (reference ``thunder/benchmarks/targets.py``):
every workload x executor stack x {fwd, fwd+bwd}, runnable as

    THUNDER_TPU_BENCH=1 pytest thunder_tpu/benchmarks/targets.py -v -s

Skipped by default (env gate) so the correctness suite stays fast; on TPU
each case prints the harness summary (median/IQR/compile split).
"""

from __future__ import annotations

import os

import pytest

from thunder_tpu.benchmarks import DEFAULT_BENCHMARKS

_RUN = os.environ.get("THUNDER_TPU_BENCH") == "1"

EXECUTOR_STACKS = {
    "xla": ["xla"],
    "pallas+xla": None,  # defaults: pallas kernels claim above XLA fusion
}

_GRAD_WORKLOADS = {"sdpa", "cross_entropy", "llama_mlp", "rms_norm", "layer_norm",
                   "gelu", "einsum", "nanogpt_csa"}


@pytest.mark.parametrize("stack", list(EXECUTOR_STACKS))
@pytest.mark.parametrize("workload", list(DEFAULT_BENCHMARKS))
def test_benchmark_forward(workload, stack):
    if not _RUN:
        pytest.skip("set THUNDER_TPU_BENCH=1 to run benchmarks")
    bench = DEFAULT_BENCHMARKS[workload]()
    stats = bench.run(executors=EXECUTOR_STACKS[stack])
    print("\n" + stats.summary())


@pytest.mark.parametrize("stack", list(EXECUTOR_STACKS))
@pytest.mark.parametrize("workload", sorted(_GRAD_WORKLOADS))
def test_benchmark_forward_backward(workload, stack):
    if not _RUN:
        pytest.skip("set THUNDER_TPU_BENCH=1 to run benchmarks")
    import numpy as np

    import thunder_tpu as tt
    from thunder_tpu import ops
    from thunder_tpu.benchmarks import Benchmark, time_fn

    bench = DEFAULT_BENCHMARKS[workload]()
    fn, args = bench.make()

    def loss_fn(*a):
        out = fn(*a)
        first = out[0] if isinstance(out, tuple) else out
        return ops.sum(ops.convert_element_type(first, tt.core.dtypes.float32)) \
            if hasattr(first, "dtype") else first

    def fwd_bwd(*a):
        return tt.value_and_grad(loss_fn)(*a)

    jfn = tt.jit(fwd_bwd, executors=EXECUTOR_STACKS[stack])
    stats = time_fn(jfn, *args, name=f"{bench.name}_fwdbwd[{stack}]")
    print("\n" + stats.summary())

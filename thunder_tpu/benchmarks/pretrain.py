"""Production-style pretraining throughput CLI.

Reference parity: ``thunder/benchmarks/benchmark_litgpt.py`` — model ×
parallelism-mode grid reporting tokens/s and model-flops utilization; here
the optimizer is part of the compiled step (the reference steps eager AdamW,
SURVEY §3.5 note).

Usage:
  python -m thunder_tpu.benchmarks.pretrain --model tiny --mode fsdp --steps 10
  python -m thunder_tpu.benchmarks.pretrain --model llama2-7b-bench --layers 2 --batch 1 --seq 2048
"""

from __future__ import annotations

import argparse
import sys
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny", help="llama config name")
    p.add_argument("--mode", default="single",
                   choices=["single", "fsdp", "hsdp", "ddp", "tp", "cp", "ep",
                            "tp_dp", "fsdp_tp"])
    p.add_argument("--replicas", type=int, default=2,
                   help="hsdp: replica-axis size (shard axis gets the rest)")
    p.add_argument("--tp", type=int, default=2,
                   help="tp_dp/fsdp_tp: tensor-parallel axis size "
                        "(the other axis gets the rest)")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--peak-tflops", type=float, default=197.0,
                   help="per-chip peak bf16 TFLOP/s (v5e=197, v5p=459)")
    p.add_argument("--devices", type=int, default=None,
                   help="force an N-device virtual CPU mesh (hermetic "
                        "distributed benchmarking without hardware)")
    p.add_argument("--data", default=None,
                   help="tokenized binary shard (.bin) to stream from via the "
                        "native input pipeline (epoch-exact shuffle, prefetch, "
                        "restart-deterministic); default: synthetic tokens")
    p.add_argument("--data-seed", type=int, default=0)
    p.add_argument("--start-step", type=int, default=0,
                   help="resume data position (the stream is a pure function "
                        "of step: restarting at step k replays exactly)")
    p.add_argument("--audit", action="store_true",
                   help="print per-step losses (costs one host sync per step "
                        "— replay verification, NOT for timing runs)")
    args = p.parse_args()

    import jax

    if args.devices:
        # jax is already imported (package __init__ pulls jax.numpy) but the
        # backend is not initialized until first use: XLA_FLAGS is read
        # lazily at backend init, and the platform switches via jax.config
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_force_host_platform_device_count={args.devices}")
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import thunder_tpu as tt
    from thunder_tpu.core.devices import MeshSpec
    from thunder_tpu.models import llama
    from thunder_tpu.optim import AdamW

    if args.mode == "ep":
        from thunder_tpu.models import mixtral as model_mod

        cfg = model_mod.CONFIGS["tiny-moe" if args.model == "tiny" else args.model]
        loss_mod = model_mod
    else:
        model_mod = llama
        cfg = llama.CONFIGS[args.model]
        loss_mod = llama
    n_layers = args.layers if args.layers is not None else cfg.n_layers
    opt = AdamW(lr=args.lr)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = tt.value_and_grad(
            lambda p: loss_mod.loss_fn(p, tokens, targets, cfg))(params)
        return loss, *opt.update(params, grads, opt_state)

    n_dev = len(jax.devices())
    if args.mode == "single":
        # donated params/opt-state: in-place updates, halves weight memory
        jstep = tt.jit(train_step, donate_argnums=(0, 1))
    elif args.mode == "fsdp":
        from thunder_tpu.distributed import fsdp

        jstep = fsdp(train_step, MeshSpec.make(fsdp=n_dev))
    elif args.mode == "hsdp":
        from thunder_tpu.distributed import hsdp

        if args.replicas < 1 or n_dev % args.replicas:
            raise SystemExit(f"--replicas {args.replicas} must divide the "
                             f"device count {n_dev} (and leave a shard axis)")
        jstep = hsdp(train_step,
                     MeshSpec.make(dp=args.replicas, fsdp=n_dev // args.replicas))
    elif args.mode == "ddp":
        from thunder_tpu.distributed import ddp

        jstep = ddp(train_step, MeshSpec.make(dp=n_dev))
    elif args.mode == "cp":
        from thunder_tpu.distributed import context_parallel

        jstep = context_parallel(train_step, MeshSpec.make(sp=n_dev))
    elif args.mode == "ep":
        from thunder_tpu.distributed import expert_parallel
        from thunder_tpu.models import mixtral

        if cfg.n_experts % n_dev:
            raise SystemExit(f"n_experts {cfg.n_experts} must be divisible "
                             f"by the device count {n_dev}")
        if args.batch % n_dev:
            raise SystemExit(f"--batch {args.batch} must be divisible by the "
                             f"device count {n_dev} (the batch shards on the ep axis)")
        jstep = expert_parallel(train_step, MeshSpec.make(ep=n_dev),
                                expert_patterns=mixtral.EP_PATTERNS)
    elif args.mode == "tp":
        from thunder_tpu.distributed import tensor_parallel

        local_cfg = llama.tp_config(cfg, n_dev)
        cfg = local_cfg
        jstep = tensor_parallel(train_step, MeshSpec.make(tp=n_dev),
                                column_patterns=llama.TP_COLUMN_PATTERNS,
                                row_patterns=llama.TP_ROW_PATTERNS)
    elif args.mode in ("tp_dp", "fsdp_tp"):
        if args.tp < 1 or n_dev % args.tp:
            raise SystemExit(f"--tp {args.tp} must divide the device count {n_dev}")
        other = n_dev // args.tp
        cfg = llama.tp_config(cfg, args.tp)
        if args.mode == "tp_dp":
            from thunder_tpu.distributed import tensor_parallel

            jstep = tensor_parallel(train_step, MeshSpec.make(dp=other, tp=args.tp),
                                    column_patterns=llama.TP_COLUMN_PATTERNS,
                                    row_patterns=llama.TP_ROW_PATTERNS,
                                    data_parallel_axis="dp")
        else:
            from thunder_tpu.distributed import fsdp_tp

            jstep = fsdp_tp(train_step, MeshSpec.make(fsdp=other, tp=args.tp),
                            column_patterns=llama.TP_COLUMN_PATTERNS,
                            row_patterns=llama.TP_ROW_PATTERNS)

    params = model_mod.init_params(cfg if args.mode == "ep" else llama.CONFIGS[args.model],
                                   seed=0, scale_layers=n_layers)
    opt_state = opt.init(params)
    if args.data:
        from thunder_tpu.data import ShardedTokenStream

        stream = ShardedTokenStream(args.data, batch=args.batch, seq=args.seq,
                                    seed=args.data_seed)

        def data_fn(step):
            t, g = stream.batch_at(step)
            return np.clip(t, 0, cfg.vocab_size - 1), \
                np.clip(g, 0, cfg.vocab_size - 1)
    else:
        rng = np.random.RandomState(0)
        fixed = rng.randint(0, cfg.vocab_size, size=(args.batch, args.seq)).astype(np.int32)
        fixed_t = np.roll(fixed, -1, 1).astype(np.int32)

        def data_fn(step):
            return fixed, fixed_t

    tokens, targets = data_fn(args.start_step)

    def force(x):
        # block_until_ready is a no-op on tunneled platforms; a ONE-ELEMENT
        # host readback (device-side slice first) is the honest sync point
        # (same as the repo-root bench.py driver metric)
        import jax.numpy as jnp

        return float(np.asarray(jnp.ravel(x)[0]))

    def force_chain(loss, params):
        force(loss)
        force(jax.tree_util.tree_leaves(params)[0])  # whole dependency chain

    t0 = time.perf_counter()
    loss, params, opt_state = jstep(params, opt_state, tokens, targets)
    force_chain(loss, params)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for k in range(args.steps):
        tokens, targets = data_fn(args.start_step + 1 + k)
        loss, params, opt_state = jstep(params, opt_state, tokens, targets)
        if args.audit:  # replay-audit mode: per-step loss (costs a sync)
            print(f"step {args.start_step + 1 + k} "
                  f"loss {float(np.asarray(loss)):.6f}", file=sys.stderr)
    force_chain(loss, params)
    dt = (time.perf_counter() - t0) / args.steps

    tokens_per_step = args.batch * args.seq
    tps = tokens_per_step / dt
    if args.mode == "ep":
        # MoE FLOPs/token: attention as dense + top_k of E expert MLPs
        base_cfg = cfg
        fpt = llama.flops_per_token(cfg, args.seq, n_layers) \
            * (1 + (cfg.top_k - 1) / max(1, cfg.n_experts))  # rough active-expert scale
    else:
        base_cfg = llama.CONFIGS[args.model]
        fpt = llama.flops_per_token(base_cfg, args.seq, n_layers)
    mfu = tps * fpt / (args.peak_tflops * 1e12 * max(1, n_dev))
    if args.mode == "ep":
        # expert-utilization report (VERDICT r2 item 10): routing health of
        # the trained params on the last batch
        import json

        from thunder_tpu.models import mixtral as _mx

        rep = _mx.expert_utilization(params, tokens, cfg)
        for li, r in enumerate(rep):
            print(f"expert-utilization layer{li}: {json.dumps(r)}", file=sys.stderr)
    print(f"model={args.model} layers={n_layers} mode={args.mode} devices={n_dev}")
    print(f"compile {compile_s:.1f}s | {dt*1e3:.1f} ms/step | {tps:,.0f} tokens/s "
          f"| MFU {mfu*100:.1f}% | loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()

"""Bandwidth-bound fp8-vs-bf16 A/B (the measurement behind FP8.md's r5
demotion of the "fp8 wins when HBM-bound" claim): decode-geometry MLP
stack where weight traffic dominates (batch 8, seq 1) — flops/byte ~8 vs
an MXU:HBM ratio of ~240, i.e. ~30x HBM-bound. Variants interleave on the
chip so tunnel weather hits each equally.

Run: python -m thunder_tpu.benchmarks.fp8_bandwidth_ab  (real TPU)
"""


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from thunder_tpu.benchmarks.breakdown import time_fn

    L, D, I, B = 4, 4096, 11008, 8
    rng = np.random.RandomState(0)

    Wg16 = [jax.device_put((rng.randn(D, I) * 0.02).astype(jnp.bfloat16)) for _ in range(L)]
    Wu16 = [jax.device_put((rng.randn(D, I) * 0.02).astype(jnp.bfloat16)) for _ in range(L)]
    Wd16 = [jax.device_put((rng.randn(I, D) * 0.02).astype(jnp.bfloat16)) for _ in range(L)]

    def to8(w):
        scale = jnp.float32(jnp.max(jnp.abs(w.astype(jnp.float32))) / 448.0)
        return (w.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn), scale
    Wg8 = [to8(w) for w in Wg16]; Wu8 = [to8(w) for w in Wu16]; Wd8 = [to8(w) for w in Wd16]
    x0 = jax.device_put((rng.randn(B, D) * 0.1).astype(jnp.bfloat16))

    @jax.jit
    def f16(x, Wg, Wu, Wd):
        for g, u, d in zip(Wg, Wu, Wd):
            h = jax.nn.silu(x @ g) * (x @ u)
            x = (h @ d).astype(jnp.bfloat16)
        return x

    @jax.jit
    def f8(x, Wg, Wu, Wd):
        for (g8, gs), (u8, us), (d8, ds) in zip(Wg, Wu, Wd):
            g = (g8.astype(jnp.bfloat16) * gs.astype(jnp.bfloat16))
            u = (u8.astype(jnp.bfloat16) * us.astype(jnp.bfloat16))
            d = (d8.astype(jnp.bfloat16) * ds.astype(jnp.bfloat16))
            h = jax.nn.silu(x @ g) * (x @ u)
            x = (h @ d).astype(jnp.bfloat16)
        return x

    @jax.jit
    def f8_fused(x, Wg, Wu, Wd):
        # dequant INSIDE the dot via f32 accumulation on the fp8-operand matmul
        # (preferred_element_type): XLA may fuse the upcast into the operand read
        for (g8, gs), (u8, us), (d8, ds) in zip(Wg, Wu, Wd):
            a = jax.lax.dot_general(x.astype(jnp.float8_e4m3fn), g8, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32) * gs
            b = jax.lax.dot_general(x.astype(jnp.float8_e4m3fn), u8, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32) * us
            h = (jax.nn.silu(a) * b).astype(jnp.float8_e4m3fn)
            x = (jax.lax.dot_general(h, d8, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32) * ds).astype(jnp.bfloat16)
        return x

    r = {}
    for name, fn, args in (("bf16 a", f16, (x0, Wg16, Wu16, Wd16)),
                           ("fp8-dequant a", f8, (x0, Wg8, Wu8, Wd8)),
                           ("fp8-fused a", f8_fused, (x0, Wg8, Wu8, Wd8)),
                           ("bf16 b", f16, (x0, Wg16, Wu16, Wd16)),
                           ("fp8-dequant b", f8, (x0, Wg8, Wu8, Wd8)),
                           ("fp8-fused b", f8_fused, (x0, Wg8, Wu8, Wd8))):
        try:
            r[name] = time_fn(fn, *args, steps=24, trials=3)
        except Exception as e:
            r[name] = None
            print(name, "FAILED:", str(e)[:90])
    wbytes16 = 3 * L * D * I * 2
    for k, v in r.items():
        if v is not None:
            print(f"{k}: {v*1e3:.2f} ms  (bf16 weight roofline {wbytes16/819e9*1e3:.2f} ms)")


if __name__ == "__main__":
    main()

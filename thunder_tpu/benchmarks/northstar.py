"""North-star evidence pack: AOT-compile the REAL BASELINE configs for the
target TPU topologies and derive the memory / communication / MFU story from
the compiled executables — no chips required.

The driver's north star (BASELINE.md) is Llama-2-7B pretraining via jit+FSDP
on a v5p-32 at >=45% MFU. This environment has one tunneled chip, so the
closest attainable evidence is exactly what the reference publishes for its
multi-GPU claim (a normalized-scaling plot, ``/root/reference/README.md:
60-63``): compile the real configs against the real topology and show, from
XLA's own accounting,

- per-device HBM fits the 95 GB budget (``memory_analysis``),
- collective bytes vs ICI bandwidth (trace-level ``comm_report``),
- cost-model step time -> projected MFU, arithmetic shown,
- the optimized HLO schedules collectives async (overlap markers).

Consumed by ``tests/test_northstar.py`` (regressions fail) and by
``python -m thunder_tpu.benchmarks.northstar`` (writes NORTHSTAR.md).
"""

from __future__ import annotations

import os

import numpy as np

# v5p chip datasheet numbers (public: jax-ml.github.io/scaling-book — the
# "How to Scale Your Model" hardware table).
V5P = {
    "peak_bf16_flops": 4.59e14,   # per chip
    "hbm_bytes": 95.74e9,         # per chip
    "hbm_bw": 2.765e12,           # bytes/s per chip
    "ici_bw_axis": 9e10,          # bytes/s one-way per link; 3 axes (3D torus)
    "ici_links": 6,
}

# topology names understood by the PJRT TPU compiler
TOPO_V5P_32 = "v5p:2x2x4"   # 16 chips = v5p-32 (cores x2 naming)
TOPO_V5P_16 = "v5p:2x2x2"   # 8 chips = v5p-16


def get_topology(name: str):
    # honor an explicit platform restriction: with JAX_PLATFORMS=cpu a
    # present-but-chipless libtpu must not be initialized — PJRT topology
    # setup blocks on the runtime socket instead of raising
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and "tpu" not in plats.split(","):
        return None
    try:
        from jax.experimental import topologies

        return topologies.get_topology_desc(platform="tpu", topology_name=name)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# abstract (no-materialization) model/optimizer state
# ---------------------------------------------------------------------------

def abstract_llama_step(cfg_name: str, *, batch: int, seq: int, n_dev: int,
                        zero: int = 2, remat: bool = False,
                        fused_loss: bool = True):
    """(jstep, args) for a FULL fwd+bwd+AdamW train step with the params and
    optimizer state as ShapeDtypeStructs — 7B compiles without 7B of host
    RAM. ``batch`` is GLOBAL."""
    import jax

    import thunder_tpu as tt
    from thunder_tpu.core.devices import MeshSpec
    from thunder_tpu.distributed import fsdp
    from thunder_tpu.models import llama
    from thunder_tpu.optim import AdamW

    cfg = llama.CONFIGS[cfg_name]
    opt = AdamW(lr=1e-4)
    loss = llama.fused_loss_fn if fused_loss else llama.loss_fn

    def train_step(params, opt_state, tokens, targets):
        loss_v, grads = tt.value_and_grad(
            lambda p: loss(p, tokens, targets, cfg, remat=remat))(params)
        new_params, new_opt = opt.update(params, grads, opt_state)
        return loss_v, new_params, new_opt

    params_abs = jax.eval_shape(lambda: llama.init_params(cfg, seed=0))
    opt_abs = jax.eval_shape(opt.init, params_abs)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    targets = np.roll(tokens, -1, 1).astype(np.int32)

    jstep = fsdp(train_step, MeshSpec.make(fsdp=n_dev), zero=zero)
    return jstep, (params_abs, opt_abs, tokens, targets), cfg


def abstract_mixtral_ep_step(*, batch: int, seq: int, n_dev: int,
                             remat: bool = True):
    import dataclasses

    import jax

    import thunder_tpu as tt
    from thunder_tpu.core import dtypes
    from thunder_tpu.core.devices import MeshSpec
    from thunder_tpu.distributed import expert_parallel
    from thunder_tpu.models import mixtral
    from thunder_tpu.optim import AdamW

    # capacity_factor 1.25 (was the 2.0 default): the r4 verdict flagged the
    # EP config's flop pad — at cf the per-expert capacity executes
    # cf x the analytic top-k flops; 1.25 keeps the measured worst-layer
    # assignment drop at 7.2% on an UNTRAINED router (MIXTRAL_EP.md sweep;
    # the aux load-balancing loss drives it toward 0 in training) and takes
    # xla_flops/analytic from 2.07x to ~1.35x at tiny scale (r5 measured)
    cfg = dataclasses.replace(mixtral.CONFIGS["mixtral-8x7b"],
                              capacity_factor=1.25)
    # the 8x7B memory recipe: all-bf16 AdamW moments (12.9B params/8 chips
    # leave no room for f32 v; the v-freeze tradeoff is documented in
    # optim.AdamW), per-block remat, chunked-vocab fused loss. Without
    # these the compile is an honest 128.6 GB/chip OOM (measured r4).
    opt = AdamW(lr=1e-4, state_dtype=dtypes.bfloat16, v_dtype=dtypes.bfloat16)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = tt.value_and_grad(
            lambda p: mixtral.fused_loss_fn(p, tokens, targets, cfg,
                                            remat=remat))(params)
        new_params, new_opt = opt.update(params, grads, opt_state)
        return loss, new_params, new_opt

    params_abs = jax.eval_shape(lambda: mixtral.init_params(cfg, seed=0))
    opt_abs = jax.eval_shape(opt.init, params_abs)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    targets = np.roll(tokens, -1, 1).astype(np.int32)

    jstep = expert_parallel(train_step, MeshSpec.make(ep=n_dev),
                            expert_patterns=mixtral.EP_PATTERNS)
    return jstep, (params_abs, opt_abs, tokens, targets), cfg


def compile_on(topo, jstep, args):
    """AOT-compile a DistributedFunction against topology devices."""
    jstep._mesh = jstep.mesh_spec.build(list(topo.devices))
    entry = jstep.compile(*args)
    assert entry.jit_obj is not None, "no whole-program jit entry"
    lowered = entry.jit_obj.lower(*entry.input_avals)
    return lowered.compile()


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

def n_params_llama(cfg) -> int:
    kv_dim = cfg.kv_heads * cfg.head_dim
    per_layer = (2 * cfg.dim                      # norms
                 + 2 * cfg.dim * cfg.dim          # wq, wo
                 + 2 * kv_dim * cfg.dim           # wk, wv
                 + 3 * cfg.intermediate_size * cfg.dim)  # gate/up/down
    return (2 * cfg.vocab_size * cfg.dim + cfg.dim
            + cfg.n_layers * per_layer)


def analytic_train_flops(n_params: int, global_tokens: int, cfg=None,
                         seq: int | None = None) -> float:
    """6*N per token (fwd 2N + bwd 4N) + attention score flops
    12*L*T*d per token (fwd+bwd, causal halving folded in)."""
    flops = 6.0 * n_params * global_tokens
    if cfg is not None and seq is not None:
        att = 12.0 * cfg.n_layers * seq * (cfg.n_heads * cfg.head_dim) // 2
        flops += att * global_tokens
    return flops


# the instruction-level collective parser is now the per-compile observe
# surface's — ONE owner (thunder_tpu/observe/census.py); the bench imports
# it back so the offline evidence pack and the live census can never drift
from thunder_tpu.observe.census import hlo_collectives  # noqa: E402


def analyze(compiled, *, n_dev: int, analytic_flops: float,
            spec=V5P) -> dict:
    """Memory + cost + roofline-projected MFU from a compiled executable."""
    ma = compiled.memory_analysis()
    mem = {k: int(getattr(ma, k, 0) or 0)
           for k in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes",
                     "alias_size_in_bytes")}
    # arguments and outputs alias (donated params/opt state) — live HBM is
    # args + temps + code (+ outputs - aliased)
    live = (mem["argument_size_in_bytes"] + mem["temp_size_in_bytes"]
            + mem["generated_code_size_in_bytes"]
            + max(0, mem["output_size_in_bytes"] - mem["alias_size_in_bytes"]))

    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = dict(ca)
    xla_flops = float(ca.get("flops", 0.0))
    hbm_bytes = float(ca.get("bytes accessed", 0.0))

    hlo = compiled.as_text()
    hlo_comm = hlo_collectives(hlo, n_dev)
    # legacy substring census kept for continuity with r4 artifacts; the
    # authoritative numbers (instruction counts, bytes, async fractions
    # WITH denominators) are in hlo_comm (VERDICT r4 #3)
    overlap = {
        "async_all_gather": hlo.count('async_collective_name="all-gather-start'),
        "async_reduce_scatter": hlo.count('async_collective_name="reduce-scatter'),
        "async_all_reduce": hlo.count('async_collective_name="all-reduce-start'),
        "all_gather_total": hlo.count("all-gather"),
        "reduce_scatter_total": hlo.count("reduce-scatter"),
        "all_reduce_total": hlo.count("all-reduce"),
        "all_to_all_total": hlo.count("all-to-all"),
    }

    # roofline projection, per device (comm term added by the caller once
    # collective bytes are known — see project()). Step TIME is bounded by
    # the flops XLA actually EXECUTES (xla_flops — e.g. the MoE capacity
    # pad); MFU's numerator stays the analytic useful flops (r5: the old
    # t_math-for-both gave the padded Mixtral config a fictitious 1.0).
    flops_dev = analytic_flops / n_dev
    t_math = flops_dev / spec["peak_bf16_flops"]
    # executed-flop time exactly as XLA counts it (0.91x analytic for the
    # causal-halved dense configs, 1.68x for the padded MoE); fall back to
    # analytic only when the backend reports no flops (CPU smoke)
    t_exec = (xla_flops / spec["peak_bf16_flops"]) if xla_flops > 0 else t_math
    t_hbm = hbm_bytes / spec["hbm_bw"]            # cost model is per-device
    t_overlapped = max(t_exec, t_hbm)
    t_serial = t_exec + t_hbm
    return {
        "memory": mem,
        "live_bytes_per_device": live,
        "fits_hbm": live < spec["hbm_bytes"],
        "xla_flops_per_device": xla_flops,
        "analytic_flops_per_device": flops_dev,
        "hbm_bytes_accessed": hbm_bytes,
        "overlap": overlap,
        "hlo_collectives": hlo_comm,
        "t_math_s": t_math,
        "t_exec_s": t_exec,
        "t_hbm_s": t_hbm,
        "step_time_overlapped_s": t_overlapped,
        "step_time_serial_s": t_serial,
        "mfu_projected_overlapped": t_math / t_overlapped,
        "mfu_projected_serial": t_math / t_serial,
    }


def comm_bytes_per_device(jstep) -> dict:
    """Trace-level collective byte counts from the examine tooling (bytes a
    single device sends/receives per step, by collective kind)."""
    from thunder_tpu.examine import comm_report

    rep = comm_report(jstep)
    return {
        "per_collective": {k: {kk: int(vv) for kk, vv in v.items()}
                           for k, v in rep["collectives"].items()},
        "total_in_bytes": int(rep["total_in_bytes"]),
        "total_out_bytes": int(rep.get("total_out_bytes", 0)),
    }


def project(metrics: dict, comm: dict, *, ici_axes_used: int = 1,
            spec=V5P) -> dict:
    """Fold the ICI term into the roofline: t_ici = received bytes / the
    ICI bandwidth actually usable (one torus axis by default — conservative;
    XLA stripes large collectives over more on a v5p 3D torus, reported as
    the _2axis variants). Step time uses EXECUTED flop time (t_exec_s);
    MFU's numerator is the analytic useful flops, capped at 1. Projections:

    - overlapped: collectives and HBM fully hidden behind the MXU
      (what the async markers show the scheduler arranging)
    - serial: nothing overlaps (hard floor)
    """
    t_math = metrics["t_math_s"]
    t_exec = metrics.get("t_exec_s", t_math)
    t_hbm = metrics["t_hbm_s"]
    t_ici = comm["total_in_bytes"] / (spec["ici_bw_axis"] * ici_axes_used)
    # absolute axis-count variants (independent of ici_axes_used, so a
    # caller passing 2 cannot silently double-discount)
    t_ici_2 = comm["total_in_bytes"] / (spec["ici_bw_axis"] * 2)
    t_over = max(t_exec, t_hbm, t_ici)
    t_serial = t_exec + t_hbm + t_ici
    t_over2 = max(t_exec, t_hbm, t_ici_2)
    t_serial2 = t_exec + t_hbm + t_ici_2
    return {
        "t_ici_s": t_ici,
        "t_ici_2axis_s": t_ici_2,
        "step_time_overlapped_s": t_over,
        "step_time_serial_s": t_serial,
        "step_time_overlapped_2axis_s": t_over2,
        "step_time_serial_2axis_s": t_serial2,
        "mfu_projected_overlapped": min(1.0, t_math / t_over),
        "mfu_projected_serial": min(1.0, t_math / t_serial),
        "mfu_projected_overlapped_2axis": min(1.0, t_math / t_over2),
        "mfu_projected_serial_2axis": min(1.0, t_math / t_serial2),
    }


def overlap_projection(entry: dict, *, spec=V5P) -> dict:
    """Re-derive a committed NORTHSTAR.json entry's roofline under the
    overlap-scheduling pass (``distributed/comm_reorder``): with the
    reduce-scatter lowering PINNED, XLA cannot rewrite zero-2's grad
    collectives into all-reduces, so the HLO recv bytes collapse from the
    measured ``recv_bytes_per_device_hlo`` (2.2x on the r5 7B run) back to
    the trace ring-model expectation — the ICI term is re-folded from
    ``recv_bytes_per_device_trace``. Pure arithmetic on the committed
    metrics (no chips): the model recorded here is the prediction the
    queued ONCHIP_AB.md pin A/B measures against."""
    recv_pinned = int(entry["recv_bytes_per_device_trace"])
    recv_hlo = int(entry["recv_bytes_per_device_hlo"])
    proj = project({"t_math_s": entry["t_math_s"],
                    "t_exec_s": entry.get("t_exec_s", entry["t_math_s"]),
                    "t_hbm_s": entry["t_hbm_s"]},
                   {"total_in_bytes": recv_pinned}, spec=spec)
    return {
        "assumes": ("pinned reduce-scatter lowering + comm_reorder schedule: "
                    "HLO recv bytes == trace ring-model expectation"),
        "recv_bytes_per_device_pinned": recv_pinned,
        "recv_bytes_per_device_unpinned_hlo": recv_hlo,
        "recv_inflation_removed": (recv_hlo / recv_pinned) if recv_pinned else 1.0,
        **proj,
        # the zero-overlap floors this pass moves (vs the committed entry)
        "mfu_serial_floor_unpinned": entry.get("mfu_projected_serial"),
        "mfu_serial_floor_unpinned_2axis": entry.get("mfu_projected_serial_2axis"),
    }


def write_overlap_models(path: str = "NORTHSTAR.json") -> dict:
    """Stamp each fsdp entry of an existing NORTHSTAR.json with its
    re-derived ``overlap_model`` block (pure arithmetic — runs without a
    TPU, unlike :func:`main`)."""
    import json

    with open(path) as f:
        results = json.load(f)
    stamped = {}
    for name, entry in results.items():
        if isinstance(entry, dict) and "recv_bytes_per_device_trace" in entry:
            entry["overlap_model"] = stamped[name] = overlap_projection(entry)
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
        f.write("\n")
    return stamped


# ---------------------------------------------------------------------------
# evidence-pack generator: python -m thunder_tpu.benchmarks.northstar
# ---------------------------------------------------------------------------

def _recv_bytes(comm: dict, n_dev: int) -> int:
    """Approximate bytes RECEIVED per device per step: for each collective,
    a device receives ~the larger of its local in/out payload minus its own
    shard — (N-1)/N of max(in, out)."""
    total = 0
    for e in comm["per_collective"].values():
        total += max(e["in_bytes"], e["out_bytes"]) * (n_dev - 1) // n_dev
    return total


def run_config(name: str, builder, topo_name: str, n_dev: int,
               global_tokens: int, n_params: int, analytic_flops: float) -> dict:
    import time as _t

    topo = get_topology(topo_name)
    if topo is None:
        raise RuntimeError(f"TPU topology {topo_name} unavailable")
    jstep, args, cfg = builder()
    t0 = _t.perf_counter()
    compiled = compile_on(topo, jstep, args)
    compile_s = _t.perf_counter() - t0
    m = analyze(compiled, n_dev=n_dev, analytic_flops=analytic_flops)
    comm = comm_bytes_per_device(jstep)
    recv_trace = _recv_bytes(comm, n_dev)
    # t_ici from the OPTIMIZED HLO's own collectives (r4 verdict #3: the
    # trace-level figure understates when XLA rewrites reduce-scatters into
    # all-reduces); trace-level kept alongside as the cross-check
    recv_hlo = m["hlo_collectives"]["recv_bytes_per_device_total"]
    recv = max(recv_hlo, recv_trace)
    proj = project(m, {"total_in_bytes": recv})
    m.update(proj)
    # throughput must reflect the post-ICI step time (code-review r5: the
    # pre-ICI figure from analyze() silently survived regeneration)
    m["tokens_per_s_per_chip_projected"] = (
        global_tokens / n_dev / proj["step_time_overlapped_s"])
    m["tokens_per_s_per_chip_projected_2axis"] = (
        global_tokens / n_dev / proj["step_time_overlapped_2axis_s"])
    m["comm"] = comm
    m["recv_bytes_per_device_trace"] = recv_trace
    m["recv_bytes_per_device_hlo"] = recv_hlo
    m["recv_bytes_per_device"] = recv
    m["compile_seconds"] = compile_s
    m["n_params"] = n_params
    m["config"] = name
    m["n_devices"] = n_dev
    m["global_tokens_per_step"] = global_tokens
    return m


def main():
    import json

    from thunder_tpu.models import llama, mixtral

    results = {}

    # 1. BASELINE config 3: Llama-2-7B FSDP(zero2) on v5p-32 (16 chips)
    cfg7 = llama.CONFIGS["llama2-7b"]
    n7 = n_params_llama(cfg7)
    results["llama2-7b-fsdp-v5p32"] = run_config(
        "llama2-7b-fsdp-v5p32",
        lambda: abstract_llama_step("llama2-7b", batch=16, seq=4096,
                                    n_dev=16, zero=2),
        TOPO_V5P_32, 16, 16 * 4096,
        n7, analytic_train_flops(n7, 16 * 4096, cfg7, 4096))
    print(json.dumps(results["llama2-7b-fsdp-v5p32"], indent=1, default=str),
          flush=True)

    # 2. BASELINE config 4: Llama-3-8B (GQA, 128k vocab, seq 8192), remat
    cfg8 = llama.CONFIGS["llama3-8b"]
    n8 = n_params_llama(cfg8)
    results["llama3-8b-fsdp-v5p32"] = run_config(
        "llama3-8b-fsdp-v5p32",
        lambda: abstract_llama_step("llama3-8b", batch=16, seq=8192,
                                    n_dev=16, zero=3, remat=True),
        TOPO_V5P_32, 16, 16 * 8192,
        n8, analytic_train_flops(n8, 16 * 8192, cfg8, 8192))
    print(json.dumps(results["llama3-8b-fsdp-v5p32"], indent=1, default=str),
          flush=True)

    # 3. BASELINE config 5: Mixtral-8x7B expert-parallel on v5p-16 (8 chips)
    mcfg = mixtral.CONFIGS["mixtral-8x7b"]
    n_m_active = 46.7e9 * 0  # computed analytically below
    # active params per token: attention + 2-of-8 experts + embeddings
    kv_dim = mcfg.kv_heads * mcfg.head_dim
    att = mcfg.n_layers * (2 * mcfg.dim * mcfg.dim + 2 * kv_dim * mcfg.dim
                           + 2 * mcfg.dim)
    expert = 3 * mcfg.intermediate_size * mcfg.dim
    router = mcfg.n_experts * mcfg.dim
    n_active = (2 * mcfg.vocab_size * mcfg.dim + mcfg.dim
                + att + mcfg.n_layers * (router + mcfg.top_k * expert))
    # batch shards over the ep axis, so global batch >= n_dev; the memory
    # lever at fixed batch is sequence length (tokens/step)
    results["mixtral-8x7b-ep-v5p16"] = run_config(
        "mixtral-8x7b-ep-v5p16",
        lambda: abstract_mixtral_ep_step(batch=8, seq=2048, n_dev=8),
        TOPO_V5P_16, 8, 8 * 2048,
        n_active, analytic_train_flops(n_active, 8 * 2048, mcfg, 2048))
    print(json.dumps(results["mixtral-8x7b-ep-v5p16"], indent=1, default=str),
          flush=True)

    with open("NORTHSTAR.json", "w") as f:
        json.dump(results, f, indent=1, default=str)
    print("wrote NORTHSTAR.json", flush=True)


if __name__ == "__main__":
    main()

"""Benchmark harness + workloads.

Reference parity: ``thunder/benchmarks/__init__.py`` (Benchmark/BenchmarkArg/
BenchmarkRunStatistics harness with median/IQR stats :53-308; nanoGPT/litgpt
module workloads :963+) re-built for JAX timing semantics
(``block_until_ready``, compile-time split out).
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence


@dataclass
class BenchmarkRunStatistics:
    name: str
    times_s: list[float]
    compile_s: float

    @property
    def median_s(self) -> float:
        return statistics.median(self.times_s)

    @property
    def mean_s(self) -> float:
        return statistics.fmean(self.times_s)

    @property
    def iqr_s(self) -> float:
        qs = statistics.quantiles(self.times_s, n=4)
        return qs[2] - qs[0]

    def summary(self) -> str:
        return (f"{self.name}: median {self.median_s*1e3:.3f} ms "
                f"(mean {self.mean_s*1e3:.3f}, iqr {self.iqr_s*1e3:.3f}, "
                f"compile {self.compile_s:.2f} s, n={len(self.times_s)})")


def _sync(out):
    """block_until_ready PLUS a one-element host readback of the first leaf:
    on tunneled platforms (axon) block_until_ready is a no-op and only a
    readback truly fences device work."""
    import jax
    import jax.numpy as jnp
    import numpy as _np

    jax.block_until_ready(out)
    leaves = [l for l in jax.tree_util.tree_leaves(out) if hasattr(l, "shape")]
    if leaves:
        _np.asarray(jnp.ravel(leaves[0])[0] if getattr(leaves[0], "ndim", 0) else leaves[0])
    return out


def time_fn(fn: Callable, *args, warmup: int = 2, iters: int = 10, name: str = "fn",
            **kwargs) -> BenchmarkRunStatistics:
    import numpy as _np
    import jax.numpy as jnp

    # device_put inputs ONCE (the whole pytree): numpy args would otherwise
    # re-upload per call (hundreds of MB over a tunneled platform — that's
    # the loader's job, not the op under measurement)
    import jax

    conv = lambda a: jnp.asarray(a) if isinstance(a, _np.ndarray) else a
    args = tuple(jax.tree_util.tree_map(conv, a) for a in args)
    kwargs = {k: jax.tree_util.tree_map(conv, v) for k, v in kwargs.items()}
    _sync(args)
    t0 = time.perf_counter()
    _sync(fn(*args, **kwargs))
    compile_s = time.perf_counter() - t0
    for _ in range(max(0, warmup - 1)):
        _sync(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return BenchmarkRunStatistics(name, times, compile_s)


@dataclass
class Benchmark:
    """A workload: produces (fn, args) pairs and derived metrics."""

    name: str
    make: Callable[[], tuple[Callable, tuple]]
    tokens_per_iter: int | None = None

    def run(self, *, executors=None, warmup: int = 2, iters: int = 10) -> BenchmarkRunStatistics:
        import thunder_tpu as tt

        fn, args = self.make()
        jfn = tt.jit(fn, executors=executors)
        label = f"{self.name}[{','.join(e if isinstance(e, str) else e.name for e in (executors or ['default']))}]"
        return time_fn(jfn, *args, warmup=warmup, iters=iters, name=label)


# ---------------------------------------------------------------------------
# workloads (reference: nanoGPT CSA/MLP/Block, litgpt GELU/SDPA, llama2 MLP,
# cross-entropy microbenchmarks — thunder/benchmarks/__init__.py:963+)
# ---------------------------------------------------------------------------

def _np_rng(seed=0):
    import numpy as np

    return np.random.RandomState(seed)


def make_sdpa_benchmark(B=8, H=16, T=1024, hd=128, causal=True, dtype="bfloat16") -> Benchmark:
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        mk = lambda: rng.randn(B, H, T, hd).astype(np.float32)
        q, k, v = mk(), mk(), mk()

        def fn(q, k, v):
            return ops.scaled_dot_product_attention(q, k, v, is_causal=causal)

        return fn, (q, k, v)

    return Benchmark(f"sdpa_B{B}H{H}T{T}D{hd}", make)


def make_cross_entropy_benchmark(N=8192, V=32000) -> Benchmark:
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        logits = rng.randn(N, V).astype(np.float32)
        tgt = rng.randint(0, V, size=(N,)).astype(np.int32)

        def fn(logits):
            return ops.cross_entropy(logits, tgt)

        return fn, (logits,)

    return Benchmark(f"cross_entropy_N{N}V{V}", make)


def make_llama_mlp_benchmark(B=8, T=1024, D=4096, I=11008) -> Benchmark:
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        x = rng.randn(B, T, D).astype(np.float32)
        wg = (rng.randn(I, D) / np.sqrt(D)).astype(np.float32)
        wu = (rng.randn(I, D) / np.sqrt(D)).astype(np.float32)
        wd = (rng.randn(D, I) / np.sqrt(I)).astype(np.float32)

        def fn(x, wg, wu, wd):
            return ops.linear(ops.mul(ops.silu(ops.linear(x, wg)), ops.linear(x, wu)), wd)

        return fn, (x, wg, wu, wd)

    return Benchmark(f"llama_mlp_B{B}T{T}D{D}I{I}", make)


def make_rmsnorm_benchmark(N=8192, D=4096) -> Benchmark:
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        x = rng.randn(N, D).astype(np.float32)
        w = rng.randn(D).astype(np.float32)

        def fn(x, w):
            return ops.rms_norm(x, w)

        return fn, (x, w)

    return Benchmark(f"rms_norm_N{N}D{D}", make)


def make_train_step_benchmark(config: str = "tiny", batch: int = 4, seq: int = 256,
                              n_layers: int | None = None) -> Benchmark:
    def make():
        import numpy as np

        import thunder_tpu as tt
        from thunder_tpu.models import llama
        from thunder_tpu.optim import AdamW

        cfg = llama.CONFIGS[config]
        params = llama.init_params(cfg, seed=0, scale_layers=n_layers)
        opt = AdamW(lr=1e-4)
        rng = _np_rng()
        tokens = rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
        targets = np.roll(tokens, -1, 1).astype(np.int32)

        def fn(params, opt_state, tokens, targets):
            loss, grads = tt.value_and_grad(
                lambda p: llama.loss_fn(p, tokens, targets, cfg))(params)
            return loss, *opt.update(params, grads, opt_state)

        return fn, (params, opt.init(params), tokens, targets)

    b = Benchmark(f"llama_{config}_train_B{batch}T{seq}", make)
    b.tokens_per_iter = batch * seq
    return b


def make_gelu_benchmark(N=8192, D=11008) -> Benchmark:
    """Reference: LitGPT GELU microbenchmark (``thunder/benchmarks/targets.py``)."""
    def make():
        import numpy as np

        from thunder_tpu import ops

        x = _np_rng().randn(N, D).astype(np.float32)

        def fn(x):
            return ops.gelu(x, approximate="tanh")

        return fn, (x,)

    return Benchmark(f"gelu_N{N}D{D}", make)


def make_layernorm_benchmark(N=8192, D=4096) -> Benchmark:
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        x = rng.randn(N, D).astype(np.float32)
        w = rng.randn(D).astype(np.float32)
        b = rng.randn(D).astype(np.float32)

        def fn(x, w, b):
            return ops.layer_norm(x, (D,), w, b)

        return fn, (x, w, b)

    return Benchmark(f"layer_norm_N{N}D{D}", make)


def make_einsum_benchmark(B=8, I=512, J=512, K=512) -> Benchmark:
    """Reference: einsum benchmark family (``thunder/benchmarks/einsum.py``)."""
    def make():
        import numpy as np

        from thunder_tpu import ops

        rng = _np_rng()
        a = rng.randn(B, I, J).astype(np.float32)
        b = rng.randn(B, J, K).astype(np.float32)

        def fn(a, b):
            return ops.einsum("bij,bjk->bik", a, b)

        return fn, (a, b)

    return Benchmark(f"einsum_bij_bjk_B{B}", make)


def make_nanogpt_attn_benchmark(B=8, T=1024, config: str = "gpt2-tiny") -> Benchmark:
    """nanoGPT causal-self-attention module (reference ``NanoGPTCSABenchmark``)."""
    def make():
        import numpy as np

        from thunder_tpu import ops
        from thunder_tpu.models import nanogpt

        cfg = nanogpt.CONFIGS[config]
        D, H = cfg.n_embd, cfg.n_head
        rng = _np_rng()
        x = rng.randn(B, T, D).astype(np.float32)
        wqkv = (rng.randn(3 * D, D) / np.sqrt(D)).astype(np.float32)
        wo = (rng.randn(D, D) / np.sqrt(D)).astype(np.float32)

        def fn(x, wqkv, wo):
            qkv = ops.linear(x, wqkv)
            q, k, v = [ops.transpose(ops.reshape(t, (B, T, H, D // H)), (0, 2, 1, 3))
                       for t in ops.chunk(qkv, 3, -1)]
            o = ops.scaled_dot_product_attention(q, k, v, is_causal=True)
            return ops.linear(ops.reshape(ops.transpose(o, (0, 2, 1, 3)), (B, T, D)), wo)

        return fn, (x, wqkv, wo)

    return Benchmark(f"nanogpt_csa_B{B}T{T}", make)


def make_nanogpt_block_benchmark(config: str = "gpt2-tiny", B=8, T=1024) -> Benchmark:
    """One full nanoGPT block fwd (reference ``NanoGPTBlockBenchmark``)."""
    def make():
        import numpy as np

        from thunder_tpu.models import nanogpt

        cfg = nanogpt.CONFIGS[config]
        params = nanogpt.init_params(cfg, seed=0, scale_layers=1)
        rng = _np_rng()
        tokens = rng.randint(0, cfg.vocab_size, size=(B, min(T, cfg.block_size))).astype(np.int32)

        def fn(params, tokens):
            return nanogpt.forward(params, tokens, cfg)

        return fn, (params, tokens)

    return Benchmark(f"nanogpt_block_B{B}", make)


DEFAULT_BENCHMARKS: dict[str, Callable[[], Benchmark]] = {
    "sdpa": make_sdpa_benchmark,
    "cross_entropy": make_cross_entropy_benchmark,
    "llama_mlp": make_llama_mlp_benchmark,
    "rms_norm": make_rmsnorm_benchmark,
    "layer_norm": make_layernorm_benchmark,
    "gelu": make_gelu_benchmark,
    "einsum": make_einsum_benchmark,
    "nanogpt_csa": make_nanogpt_attn_benchmark,
    "nanogpt_block": make_nanogpt_block_benchmark,
    "train_step": make_train_step_benchmark,
}

"""Benchmark CLI: compare executors on a workload.

Usage:
  python -m thunder_tpu.benchmarks --workload sdpa --executors pallas,xla xla
  python -m thunder_tpu.benchmarks --workload train_step

Reference parity: the pytest-benchmark target grid
(``thunder/benchmarks/targets.py``) as a plain CLI.
"""

import argparse

from thunder_tpu.benchmarks import DEFAULT_BENCHMARKS


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workload", default="sdpa", choices=sorted(DEFAULT_BENCHMARKS))
    p.add_argument("--executors", nargs="*", default=["xla", "pallas,xla"],
                   help="comma-joined executor lists to compare")
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    bench = DEFAULT_BENCHMARKS[args.workload]()
    for exs in args.executors:
        stats = bench.run(executors=exs.split(","), iters=args.iters)
        line = stats.summary()
        if bench.tokens_per_iter:
            line += f"  ({bench.tokens_per_iter / stats.median_s:.0f} tokens/s)"
        print(line)


if __name__ == "__main__":
    main()

"""Elastic training: checkpoint-restart supervision + failure detection.

NEW capability — the reference has **no** elastic runtime, rank-failure
handling, or fault injection (SURVEY §5 "Failure detection / elastic
recovery: Absent"). TPU-native approach: JAX SPMD jobs cannot mask a lost
chip inside a step, so elasticity = frequent cheap sharded checkpoints +
supervised restart — this module provides both halves:

- ``CheckpointManager``: rotating step checkpoints (orbax-backed via
  ``thunder_tpu.checkpoint``; each process writes its owned shards), atomic
  latest-pointer, restore-onto-any-mesh (the template carries the new
  shardings, so a v5p-64 job can resume on v5p-32).
- ``ElasticTrainer``: runs the compiled step under supervision — on a step
  failure (device error, preemption signal, injected fault) it restores the
  last checkpoint and replays. Data must be addressable by step
  (``data_fn(step) -> batch``) so replays are deterministic.
- ``Heartbeat`` / ``check_stalled``: liveness file for external watchdogs
  (a hung collective doesn't raise — the watchdog kills and the supervisor
  restarts from the checkpoint).
- ``FaultInjector``: deterministic fault injection for testing recovery
  paths (the reference has nothing to test recovery *with*).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Callable

from thunder_tpu.checkpoint import (load_checkpoint, save_checkpoint,
                                    wait_for_checkpoints)


class CheckpointManager:
    """Rotating step checkpoints under ``root/step_N`` with a ``LATEST``
    pointer written only after a successful save (atomic rename).

    ``asynchronous=True``: saves overlap training with a depth-1 pipeline —
    requesting save N first JOINS save N-1 and flips LATEST to it, then
    kicks off N in the background. LATEST therefore always names a
    fully-committed checkpoint; call :meth:`finalize` (ElasticTrainer does)
    before exiting so the last save commits too."""

    def __init__(self, root: str, keep: int = 3, asynchronous: bool = False):
        self.root = os.path.abspath(root)
        self.keep = keep
        self.asynchronous = asynchronous
        self._pending: int | None = None
        os.makedirs(self.root, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step}")

    def _write_latest(self, step: int) -> None:
        tmp = os.path.join(self.root, ".LATEST.tmp")
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
        os.replace(tmp, os.path.join(self.root, "LATEST"))

    def _commit_pending(self) -> None:
        if self._pending is None:
            return
        # join only OUR pending save — other managers' in-flight saves are
        # their business (per-path checkpointers, no shared singleton)
        wait_for_checkpoints(self._step_dir(self._pending))
        self._write_latest(self._pending)
        self._pending = None
        self._gc()

    def finalize(self) -> None:
        """Join and commit any in-flight asynchronous save."""
        self._commit_pending()

    def save(self, step: int, state: Any) -> None:
        d = self._step_dir(step)
        if self.asynchronous:
            # join the in-flight save BEFORE any delete: re-saving the
            # pending step must not rmtree a directory being written
            self._commit_pending()
            if os.path.exists(d):
                shutil.rmtree(d)
            was_async = save_checkpoint(d, state, asynchronous=True)
            if not was_async:
                # sync fallback (no orbax): the data is already on disk —
                # deferring LATEST would leave a committed checkpoint
                # unreferenced across a crash for no benefit (advisor r3)
                self._write_latest(step)
                self._gc()
                return
            self._pending = step
            return
        if os.path.exists(d):
            shutil.rmtree(d)
        save_checkpoint(d, state)
        self._write_latest(step)
        self._gc()

    def latest_step(self) -> int | None:
        p = os.path.join(self.root, "LATEST")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return int(json.load(f)["step"])

    def restore_latest(self, template: Any | None = None) -> tuple[int, Any] | None:
        self._commit_pending()
        step = self.latest_step()
        if step is None:
            return None
        return step, load_checkpoint(self._step_dir(step), template)

    def _gc(self) -> None:
        steps = sorted(
            int(d.split("_", 1)[1]) for d in os.listdir(self.root)
            if d.startswith("step_") and d.split("_", 1)[1].isdigit())
        for s in steps[:-self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


class Heartbeat:
    """Liveness file for external watchdogs: ``beat(step)`` each step;
    ``check_stalled`` (anywhere) reports if the trainer stopped making
    progress — the detector for hangs that never raise."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def beat(self, step: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
        os.replace(tmp, self.path)


def check_stalled(heartbeat_path: str, timeout_s: float) -> bool:
    try:
        with open(heartbeat_path) as f:
            last = json.load(f)["time"]
    except Exception:
        return False
    return (time.time() - last) > timeout_s


class FaultInjector:
    """Raise a fault at chosen steps (testing harness for recovery paths)."""

    def __init__(self, fail_at: set[int] | None = None, exc=RuntimeError,
                 repeat: bool = False):
        self.fail_at = set(fail_at or ())
        self.exc = exc
        self.repeat = repeat  # True = permanent fault (fires on every replay)
        self.fired: set[int] = set()

    def maybe_fail(self, step: int) -> None:
        if step in self.fail_at and (self.repeat or step not in self.fired):
            self.fired.add(step)
            raise self.exc(f"injected fault at step {step}")


class ElasticTrainer:
    """Supervised training loop with checkpoint-restart recovery.

    ``step_fn(state, batch) -> state`` (state is any pytree; put the loss in
    it if you want it logged). ``data_fn(step) -> batch`` must be
    deterministic in ``step`` so replay after restore is exact.
    """

    RETRYABLE = (RuntimeError, OSError)

    def __init__(self, step_fn: Callable, ckpt: CheckpointManager, *,
                 save_every: int = 100, max_restarts: int = 3,
                 heartbeat: Heartbeat | None = None,
                 fault_injector: FaultInjector | None = None,
                 on_event: Callable[[str, dict], None] | None = None):
        self.step_fn = step_fn
        self.ckpt = ckpt
        self.save_every = save_every
        self.max_restarts = max_restarts
        self.heartbeat = heartbeat
        self.fault_injector = fault_injector
        self.on_event = on_event or (lambda kind, info: None)
        self.restarts = 0

    def run(self, state: Any, data_fn: Callable[[int], Any], n_steps: int) -> Any:
        # resume from the latest checkpoint if one exists (process restart)
        restored = self.ckpt.restore_latest(template=state)
        start = 0
        if restored is not None:
            start, state = restored
            self.on_event("resume", {"step": start})
        step = start
        while step < n_steps:
            try:
                if self.fault_injector is not None:
                    self.fault_injector.maybe_fail(step)
                state = self.step_fn(state, data_fn(step))
                step += 1
                if self.heartbeat is not None:
                    self.heartbeat.beat(step)
                if step % self.save_every == 0 or step == n_steps:
                    self.ckpt.save(step, state)
                if step == n_steps and hasattr(self.ckpt, "finalize"):
                    self.ckpt.finalize()
            except self.RETRYABLE as e:
                self.restarts += 1
                self.on_event("failure", {"step": step, "error": repr(e),
                                          "restart": self.restarts})
                if self.restarts > self.max_restarts:
                    raise
                restored = self.ckpt.restore_latest(template=state)
                if restored is None:
                    step = start
                    self.on_event("restart_from_scratch", {"step": step})
                else:
                    step, state = restored
                    self.on_event("restart", {"step": step})
        return state

"""Elastic training: checkpoint-restart supervision + failure detection.

NEW capability — the reference has **no** elastic runtime, rank-failure
handling, or fault injection (SURVEY §5 "Failure detection / elastic
recovery: Absent"). TPU-native approach: JAX SPMD jobs cannot mask a lost
chip inside a step, so elasticity = frequent cheap sharded checkpoints +
supervised restart — this module provides both halves, built on the
``thunder_tpu.runtime`` fault-domain subsystem:

- ``CheckpointManager``: rotating step checkpoints (orbax-backed via
  ``thunder_tpu.checkpoint``; each process writes its owned shards), commit
  markers + atomic latest-pointer (a crash between the data write and the
  LATEST flip leaves a *torn* step dir: it never counts toward retention,
  is swept at writer startup, and a torn/unreadable LATEST falls back to
  the newest committed marker), restore-onto-any-mesh.
- ``ElasticTrainer``: runs the compiled step under supervision — failures
  are classified (``runtime.retry``: retryable / fatal / degradable),
  recovered with jittered exponential backoff under a sliding-window
  restart budget, SIGTERM preemption commits a checkpoint and exits
  cleanly, and a warm restart reuses the persistent compile cache
  (``compile_cache_dir`` → ``enable_compilation_cache``) so replay costs
  seconds, not a fresh NORTHSTAR-scale compile.
- ``Heartbeat`` / ``check_stalled`` / ``Watchdog``: liveness file +
  in-process watchdog thread for hangs that never raise (a stuck
  collective); a heartbeat that is *never written* reads as stalled after
  a grace period — a trainer that dies before its first beat is flagged.
- ``FaultInjector``: the legacy step-level injector (kept for
  compatibility); new chaos tests use ``runtime.faults.FaultPlan`` which
  reaches every layer (compile, dispatch, kernels, collectives,
  checkpoint IO) — see ``thunder_tpu/runtime/faults.py``.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import threading
import time
from typing import Any, Callable

from thunder_tpu.checkpoint import (load_checkpoint, save_checkpoint,
                                    wait_for_checkpoints)
from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime import retry as _retry
from thunder_tpu.runtime import sentinel as _sentinel
from thunder_tpu.runtime.faults import FaultPlan
from thunder_tpu.runtime.retry import RestartBudget, RetryPolicy
from thunder_tpu.runtime.sentinel import NumericsPolicy


class CheckpointManager:
    """Rotating step checkpoints under ``root/step_N`` with a per-dir commit
    marker and a ``LATEST`` pointer written only after a successful save
    (atomic rename).

    Commit protocol: data lands in ``step_N``, then ``step_N/.committed``
    is written, then ``LATEST`` flips (atomic replace). A crash anywhere
    before the marker leaves a torn dir that (a) never counts toward the
    ``keep`` retention window, (b) is swept when the next *writer* starts
    (first ``save`` / supervisor startup — see :meth:`sweep_uncommitted`),
    and (c) can never be selected by ``latest_step`` — which also falls
    back to the newest committed marker when ``LATEST`` itself is missing
    or torn. ``_gc`` deletes only *committed* dirs beyond ``keep`` and
    never the dir ``LATEST`` references.

    ``asynchronous=True``: saves overlap training with a depth-1 pipeline —
    requesting save N first JOINS save N-1 and flips LATEST to it, then
    kicks off N in the background. LATEST therefore always names a
    fully-committed checkpoint; call :meth:`finalize` (ElasticTrainer does)
    before exiting so the last save commits too."""

    COMMIT_MARKER = ".committed"

    def __init__(self, root: str, keep: int = 3, asynchronous: bool = False):
        self.root = os.path.abspath(root)
        self.keep = keep
        self.asynchronous = asynchronous
        self._pending: int | None = None
        self._swept = False
        os.makedirs(self.root, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step}")

    def _step_dirs(self) -> list[int]:
        return sorted(
            int(d.split("_", 1)[1]) for d in os.listdir(self.root)
            if d.startswith("step_") and d.split("_", 1)[1].isdigit())

    def _is_committed(self, step: int) -> bool:
        return os.path.exists(os.path.join(self._step_dir(step), self.COMMIT_MARKER))

    def _committed_steps(self) -> list[int]:
        return [s for s in self._step_dirs() if self._is_committed(s)]

    def _latest_from_pointer(self) -> int | None:
        try:
            with open(os.path.join(self.root, "LATEST")) as f:
                return int(json.load(f)["step"])
        except Exception:
            return None  # missing or torn: caller falls back to markers

    def sweep_uncommitted(self) -> None:
        """Writer-startup sweep: a step dir without a commit marker is a
        torn write from a crashed process — remove it so it can never
        shadow a committed checkpoint or distort retention. The dir
        ``LATEST`` references is always kept (pre-marker-era checkpoints
        commit via the pointer alone).

        Deliberately NOT run from ``__init__``: a manager constructed only
        to *read* (``latest_step``/``restore_latest`` from a monitoring
        process) must never delete another writer's in-flight save, which
        is indistinguishable from a torn dir until its marker lands. The
        first :meth:`save` runs it (this process is then the root's
        writer, and its own saves haven't started), as does
        ``ElasticTrainer.run`` at supervisor startup.

        Only unmarked dirs ABOVE the committed latest are removed: a crash
        tears the save in flight, which is always the newest step; dirs at
        or below LATEST may be pre-marker-era committed checkpoints (valid
        rollback points), so they are never touched."""
        self._swept = True
        latest = self.latest_step()
        if latest is None:
            return  # no committed anchor: never delete blindly
        for s in self._step_dirs():
            if s <= latest or s == self._pending or self._is_committed(s):
                continue
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _write_latest(self, step: int) -> None:
        # marker FIRST: if we crash between the two writes, the fallback
        # scan in latest_step still finds this fully-written checkpoint
        d = self._step_dir(step)
        if not os.path.isdir(d):
            return  # the dir vanished (external cleanup): LATEST must not
            # be flipped to a checkpoint that no longer exists
        with open(os.path.join(d, self.COMMIT_MARKER), "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
        tmp = os.path.join(self.root, ".LATEST.tmp")
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
        os.replace(tmp, os.path.join(self.root, "LATEST"))

    def _commit_pending(self) -> None:
        if self._pending is None:
            return
        # join only OUR pending save — other managers' in-flight saves are
        # their business (per-path checkpointers, no shared singleton)
        wait_for_checkpoints(self._step_dir(self._pending))
        self._write_latest(self._pending)
        self._pending = None
        self._gc()

    def finalize(self) -> None:
        """Join and commit any in-flight asynchronous save."""
        self._commit_pending()

    def save(self, step: int, state: Any) -> None:
        if not self._swept:
            self.sweep_uncommitted()  # first write: this manager owns the root
        d = self._step_dir(step)
        if self.asynchronous:
            # join the in-flight save BEFORE any delete: re-saving the
            # pending step must not rmtree a directory being written
            self._commit_pending()
            if os.path.exists(d):
                shutil.rmtree(d)
            was_async = save_checkpoint(d, state, asynchronous=True)
            if not was_async:
                # sync fallback (no orbax): the data is already on disk —
                # deferring LATEST would leave a committed checkpoint
                # unreferenced across a crash for no benefit (advisor r3)
                self._write_latest(step)
                self._gc()
                return
            self._pending = step
            return
        if os.path.exists(d):
            shutil.rmtree(d)
        save_checkpoint(d, state)
        self._write_latest(step)
        self._gc()

    def latest_step(self) -> int | None:
        step = self._latest_from_pointer()
        if step is not None and os.path.isdir(self._step_dir(step)):
            return step
        # LATEST missing/torn (crash mid-flip): newest committed marker wins
        committed = self._committed_steps()
        return committed[-1] if committed else None

    def restore_latest(self, template: Any | None = None) -> tuple[int, Any] | None:
        self._commit_pending()
        step = self.latest_step()
        if step is None:
            return None
        return step, load_checkpoint(self._step_dir(step), template)

    def _gc(self) -> None:
        # retention counts COMMITTED checkpoints only: torn dirs (crash
        # between save and the LATEST flip) must neither occupy keep slots
        # nor push the LATEST-committed checkpoint out of the window — and
        # the dir LATEST references is never deleted, whatever `keep` says
        latest = self._latest_from_pointer()
        committed = self._committed_steps()
        for s in committed[:-self.keep]:
            if s == latest:
                continue
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


class Heartbeat:
    """Liveness file for external watchdogs: ``beat(step)`` each step;
    ``check_stalled`` (anywhere) reports if the trainer stopped making
    progress — the detector for hangs that never raise."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def beat(self, step: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
        os.replace(tmp, self.path)


# first time each heartbeat path was observed missing/unreadable: the
# anchor for the missing-heartbeat grace period (a trainer that dies
# before its first beat must eventually read as stalled)
_first_missing: dict[str, float] = {}


def check_stalled(heartbeat_path: str, timeout_s: float, *,
                  grace_s: float | None = None, _now: float | None = None) -> bool:
    """True when the trainer behind ``heartbeat_path`` stopped progressing.

    A present heartbeat is stalled when older than ``timeout_s``. A missing
    or unreadable heartbeat is stalled once it has *stayed* missing for
    ``grace_s`` (default: ``timeout_s``) since this checker first looked —
    previously a never-written beat read as healthy forever, so a trainer
    that died before its first step was never flagged."""
    now = time.time() if _now is None else _now
    path = os.path.abspath(heartbeat_path)
    try:
        with open(path) as f:
            last = json.load(f)["time"]
    except Exception:
        first = _first_missing.setdefault(path, now)
        grace = timeout_s if grace_s is None else grace_s
        return (now - first) > grace
    _first_missing.pop(path, None)
    return (now - last) > timeout_s


class Watchdog:
    """In-process heartbeat watchdog thread with escalation.

    Polls the heartbeat file, exports its age as the
    ``runtime.heartbeat_age_s`` gauge, and calls ``escalate(age_s)`` once
    per stall episode (a fresh beat re-arms it). A heartbeat never written
    at all escalates after ``grace_s`` (default ``timeout_s``) — the
    in-process form of the :func:`check_stalled` fix."""

    def __init__(self, heartbeat_path: str, timeout_s: float, *,
                 poll_s: float | None = None, grace_s: float | None = None,
                 escalate: Callable[[float], None] | None = None):
        self.path = os.path.abspath(heartbeat_path)
        self.timeout_s = timeout_s
        self.grace_s = timeout_s if grace_s is None else grace_s
        self.poll_s = poll_s if poll_s is not None else max(timeout_s / 4.0, 0.01)
        self.escalate = escalate or (lambda age_s: None)
        self.stalled = False
        self.escalations = 0
        self._stop = threading.Event()
        self._started_at: float | None = None
        self._missing_since: float | None = None
        self._thread: threading.Thread | None = None

    def _beat_age(self) -> float | None:
        try:
            with open(self.path) as f:
                return max(time.time() - json.load(f)["time"], 0.0)
        except Exception:
            return None

    def _check_once(self) -> None:
        age = self._beat_age()
        if age is not None:
            self._missing_since = None
            _observe.set_gauge("runtime.heartbeat_age_s", age)
            stalled = age > self.timeout_s
        else:
            # grace anchored at when the beat FIRST went missing (a beat
            # that disappears after an hour of health must get the full
            # grace window, not escalate instantly)
            now = time.monotonic()
            if self._missing_since is None:
                self._missing_since = now
            waited = now - self._missing_since
            _observe.set_gauge("runtime.heartbeat_age_s", waited)
            stalled = waited > self.grace_s
            age = waited
        if stalled and not self.stalled:
            self.stalled = True
            self.escalations += 1
            _observe.inc("runtime.watchdog_escalations")
            _observe.event("watchdog_stalled", heartbeat=self.path, age_s=age)
            self.escalate(age)
        elif not stalled:
            self.stalled = False  # fresh beat re-arms escalation

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._check_once()

    def start(self) -> "Watchdog":
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="thunder-tpu-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class FaultInjector:
    """Legacy step-level injector, now a thin facade over
    ``runtime.faults.FaultPlan`` — ONE injection surface for the whole
    stack. The old constructor signature (``fail_at`` / ``exc`` /
    ``repeat``) keeps working; under the hood it builds a ``step``-domain
    :class:`~thunder_tpu.runtime.faults.FaultSpec` (``repeat=True`` maps to
    ``transient=False``), so schedules, metrics (``runtime.faults_injected``)
    and events flow through the same machinery as every other domain. New
    code should pass ``fault_plan=`` to :class:`ElasticTrainer` directly."""

    def __init__(self, fail_at: set[int] | None = None, exc=RuntimeError,
                 repeat: bool = False):
        from thunder_tpu.runtime.faults import FaultSpec

        self.fail_at = set(fail_at or ())
        self.exc = exc
        self.repeat = repeat  # True = permanent fault (fires on every replay)
        self._spec = FaultSpec("step", at_steps=self.fail_at,
                               transient=not repeat, exc=exc) \
            if self.fail_at else None
        self.plan = FaultPlan([self._spec] if self._spec is not None else [])

    @property
    def fired(self) -> set[int]:
        """Steps at which this injector has fired (legacy inspection API)."""
        return set(self._spec._fired_steps) if self._spec is not None else set()

    def maybe_fail(self, step: int) -> None:
        self.plan.maybe_fail("step", step=step)


class ElasticTrainer:
    """Supervised training loop with checkpoint-restart recovery.

    ``step_fn(state, batch) -> state`` (state is any pytree; put the loss in
    it if you want it logged). ``data_fn(step) -> batch`` must be
    deterministic in ``step`` so replay after restore is exact.

    Supervision policy:

    - failures are classified via ``runtime.retry.classify`` — ``fatal``
      exceptions (KeyboardInterrupt, programming errors) propagate
      immediately; everything else restores the last checkpoint and
      replays,
    - restarts draw from a **sliding-window budget**: at most
      ``max_restarts`` restarts per ``restart_window_s`` seconds
      (``None`` = lifetime, the legacy behavior),
    - consecutive failures back off with ``retry_policy`` (jittered
      exponential; ``None`` = restart immediately),
    - SIGTERM (TPU preemption notice) sets a flag; after the in-flight step
      completes the trainer commits a checkpoint, emits ``preempted``, and
      returns cleanly — a fresh process resumes from that exact step,
    - ``watchdog_timeout_s`` starts an in-process :class:`Watchdog` on the
      heartbeat (escalates through ``on_event("stalled", ...)``),
    - ``compile_cache_dir`` enables the persistent compile cache (and the
      kernel-quarantine set next to it) so the post-restart replay recompiles
      from disk in seconds,
    - ``numerics_policy`` arms the numerical-integrity response ladder: it
      is installed process-wide for the duration of ``run()`` so any
      ``NumericsGuardTransform``-guarded step jitted without an explicit
      policy follows it. Non-finite steps are skipped *in-graph* by the
      guard (``runtime.skipped_steps``); a ``LossSpike`` raised by the
      sentinel is classified retryable and handled as a **rewind** — the
      trainer restores the last committed checkpoint and replays the data
      order exactly (``runtime.rewinds``, ``on_event("rewind", ...)``);
      persistent non-finite output triggers the sentinel's kernel bisection
      inside the jit driver before anything reaches this loop.
    """

    RETRYABLE = (RuntimeError, OSError)  # legacy alias; classification has
    # moved to thunder_tpu.runtime.retry.classify

    def __init__(self, step_fn: Callable, ckpt: CheckpointManager, *,
                 save_every: int = 100, max_restarts: int = 3,
                 restart_window_s: float | None = None,
                 retry_policy: RetryPolicy | None = None,
                 heartbeat: Heartbeat | None = None,
                 watchdog_timeout_s: float | None = None,
                 fault_injector: FaultInjector | None = None,
                 fault_plan: FaultPlan | None = None,
                 numerics_policy: NumericsPolicy | None = None,
                 numerics_sentinels=(),
                 compile_cache_dir: str | None = None,
                 handle_preemption: bool = True,
                 preempt_signals=(signal.SIGTERM,),
                 on_event: Callable[[str, dict], None] | None = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if watchdog_timeout_s is not None and heartbeat is None:
            raise ValueError("watchdog_timeout_s requires heartbeat= (the "
                             "watchdog watches the heartbeat file)")
        self.step_fn = step_fn
        self.ckpt = ckpt
        self.save_every = save_every
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.retry_policy = retry_policy
        self.heartbeat = heartbeat
        self.watchdog_timeout_s = watchdog_timeout_s
        self.fault_injector = fault_injector
        self.fault_plan = fault_plan
        self.numerics_policy = numerics_policy
        # sentinels whose guarded steps this trainer replays (e.g.
        # [guard.sentinel]); when given, restart refold-suppression is
        # delivered to exactly these instead of the process-wide broadcast
        # (several independent trainers/guards in one process: a broadcast
        # would freeze the EWMAs of guards this trainer never replays)
        self.numerics_sentinels = tuple(numerics_sentinels)
        self.compile_cache_dir = compile_cache_dir
        self.handle_preemption = handle_preemption
        self.preempt_signals = tuple(preempt_signals)
        self.on_event = on_event or (lambda kind, info: None)
        self.sleep_fn = sleep_fn
        self.clock = clock
        self.restarts = 0
        self.backoffs: list[float] = []  # delays actually slept (inspection)
        self._budget = RestartBudget(max_restarts, restart_window_s, clock=clock)
        self._preempted = False

    def request_preemption(self) -> None:
        """Ask the trainer to checkpoint and exit after the current step
        (what the SIGTERM handler calls; usable directly from tests or a
        cluster-notice poller thread)."""
        self._preempted = True

    # -- run ----------------------------------------------------------------
    def run(self, state: Any, data_fn: Callable[[int], Any], n_steps: int) -> Any:
        if self.compile_cache_dir is not None:
            # warm restart: executables (and the kernel-quarantine set) come
            # from disk, so the post-crash replay compiles in seconds
            import thunder_tpu as tt

            tt.enable_compilation_cache(self.compile_cache_dir)
        installed: dict[int, Any] = {}
        if self.handle_preemption:
            def _on_signal(signum, frame):
                self._preempted = True
                self.on_event("preempt_signal", {"signum": signum})
                _observe.event("preempt_signal", signum=signum)

            for sig in self.preempt_signals:
                try:
                    installed[sig] = signal.signal(sig, _on_signal)
                except ValueError:  # not the main thread: rely on
                    pass            # request_preemption()
        if hasattr(self.ckpt, "sweep_uncommitted"):
            # supervisor startup: this process is the root's writer — torn
            # dirs from the previous incarnation's crash are removed now
            self.ckpt.sweep_uncommitted()
        watchdog = None
        if self.watchdog_timeout_s is not None and self.heartbeat is not None:
            watchdog = Watchdog(
                self.heartbeat.path, self.watchdog_timeout_s,
                escalate=lambda age: self.on_event("stalled", {"age_s": age}),
            ).start()
        prev_policy = None
        if self.numerics_policy is not None:
            # process-installed for the supervision scope: guards jitted
            # without an explicit policy follow the trainer's ladder
            prev_policy = _sentinel.install_policy(self.numerics_policy)
        try:
            return self._run_supervised(state, data_fn, n_steps)
        finally:
            if self.numerics_policy is not None:
                _sentinel.install_policy(prev_policy)
            if watchdog is not None:
                watchdog.stop()
            for sig, old in installed.items():
                signal.signal(sig, old)

    def _run_supervised(self, state, data_fn, n_steps):
        # resume from the latest checkpoint if one exists (process restart)
        restored = self.ckpt.restore_latest(template=state)
        start = 0
        if restored is not None:
            start, state = restored
            self.on_event("resume", {"step": start})
        # recovery baseline: a failure BEFORE the first periodic save finds
        # no checkpoint — replaying on top of already-advanced state would
        # double-apply steps, so restart-from-scratch resets to this state
        initial_state = state
        step = start
        consecutive_failures = 0
        while step < n_steps:
            if self._preempted:
                # the in-flight step has completed: commit and exit cleanly
                self.ckpt.save(step, state)
                if hasattr(self.ckpt, "finalize"):
                    self.ckpt.finalize()
                self.on_event("preempted", {"step": step})
                _observe.event("preempted", step=step)
                return state
            try:
                if self.fault_plan is not None:
                    self.fault_plan.maybe_fail("step", step=step)
                if self.fault_injector is not None:
                    self.fault_injector.maybe_fail(step)
                state = self.step_fn(state, data_fn(step))
                step += 1
                consecutive_failures = 0
                if self.heartbeat is not None:
                    self.heartbeat.beat(step)
                if step % self.save_every == 0 or step == n_steps:
                    self.ckpt.save(step, state)
                if step == n_steps and hasattr(self.ckpt, "finalize"):
                    self.ckpt.finalize()
            except BaseException as e:
                if _retry.classify(e) == _retry.FATAL:
                    raise
                t_fail = time.perf_counter()
                failed_step = step
                self.restarts += 1
                consecutive_failures += 1
                self.on_event("failure", {"step": step, "error": repr(e),
                                          "restart": self.restarts})
                _observe.inc("runtime.restarts")
                if not self._budget.record():
                    self.on_event("restart_budget_exhausted",
                                  {"in_window": self._budget.in_window,
                                   "window_s": self.restart_window_s})
                    raise
                if self.retry_policy is not None:
                    delay = self.retry_policy.delay_s(consecutive_failures)
                    if delay > 0:
                        self.backoffs.append(delay)
                        self.on_event("backoff", {"delay_s": delay,
                                                  "attempt": consecutive_failures})
                        _observe.inc("runtime.retries")
                        _observe.observe_value("runtime.backoff_ms", delay * 1e3)
                        self.sleep_fn(delay)
                restored = self.ckpt.restore_latest(template=state)
                if restored is None:
                    step = start
                    state = initial_state
                    self.on_event("restart_from_scratch", {"step": step})
                else:
                    step, state = restored
                    self.on_event("restart", {"step": step})
                if isinstance(e, _sentinel.LossSpike):
                    # numerics ladder rung 2: the sentinel judged a finite
                    # loss anomalous and the restore above just happened —
                    # only NOW is this a rewind (not before the budget gate:
                    # an exhausted budget re-raises without ever restoring).
                    # The deterministic data_fn makes the replay order exact;
                    # tell the sentinel how many already-folded steps are
                    # about to replay so it re-judges without re-folding.
                    _observe.inc("runtime.rewinds")
                    _observe.event("sentinel_rewind", step=failed_step,
                                   loss=e.loss, z=e.z)
                    self.on_event("rewind", {"step": failed_step,
                                             "loss": e.loss, "z": e.z})
                    if getattr(e, "sentinel", None) is not None:
                        e.sentinel.notify_rewind(failed_step - step)
                elif self.numerics_policy is not None:
                    # an armed trainer's ORDINARY restart also replays
                    # already-folded steps — suppress those refolds too, or
                    # every crash recovery deflates the EWMA variance (no
                    # exception-carried sentinel here: deliver to the
                    # explicitly-owned sentinels, else broadcast)
                    if self.numerics_sentinels:
                        for s in self.numerics_sentinels:
                            s.notify_rewind(failed_step - step)
                    else:
                        _sentinel.notify_rewind_all(failed_step - step)
                # time-to-recover: failure -> state restored, replay ready
                _observe.observe_value("runtime.recovery_ms",
                                       (time.perf_counter() - t_fail) * 1e3)
        return state

"""thunder_tpu: a TPU-native deep-learning trace compiler.

``thunder_tpu.jit(fn)`` acquires the user's program as a printable,
multi-stage trace over a small primitive set; trace transforms provide
autograd (``value_and_grad`` inlined for whole-train-step compilation),
distributed parallelism, and optimization passes; a prioritized executor
system dispatches operations — an eager ``jax.numpy`` fallback, an XLA
fusion executor, and Pallas kernel executors.

Capability parity with lightning-thunder's driver
(``thunder/__init__.py:262`` jit, ``CompileData/CompileStats``
``thunder/common.py:57,181``, cache ``CacheEntry`` ``thunder/__init__.py:242``,
introspection ``last_traces`` ``:859-944``) — re-architected TPU-first:
constant-values caching keyed on input metadata, functional RNG, no
bytecode interpreter (JAX-style duck tracing).
"""

from __future__ import annotations

import os as _os
import time
from numbers import Number
from typing import Any, Callable, Sequence

import numpy as _np

from thunder_tpu.core import dtypes, devices, prims
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.proxies import NumberProxy, Proxy, StringProxy, TensorProxy
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.trace import TraceCtx, TraceResults, get_tracectx, tracectx
from thunder_tpu.core.transform_common import Transform, cse, dce
from thunder_tpu.core.transforms import (
    forward_and_backward_from_trace,
    inline_value_and_grad,
    jvp_call,
    vmap_call,
)
# load the checkpoint-IO SUBMODULE first: the import system sets the package's
# ``checkpoint`` attribute to the module exactly once (at first load), so
# importing it eagerly here — before the function binding below — means a later
# ``from thunder_tpu.checkpoint import save_checkpoint`` elsewhere can never
# shadow ``tt.checkpoint`` (the activation-checkpoint function) back to a module
import thunder_tpu.checkpoint as checkpoint_io  # noqa: F401
from thunder_tpu.core.rematerialization import (
    checkpoint,
    rematerialize_forward_and_backward,
)
from thunder_tpu import observe  # noqa: F401  (thunder_tpu.observe.*)
from thunder_tpu.observe import registry as _observe
from thunder_tpu import runtime as runtime  # noqa: F401  (fault-domain runtime)
from thunder_tpu.runtime import faults as _faults
from thunder_tpu.runtime import quarantine as _quarantine
from thunder_tpu.runtime import sentinel as _sentinel
from thunder_tpu.runtime.faults import KernelExecutionError

__version__ = "0.1.0"

_CACHE_OPTIONS = ("constant values", "symbolic values", "no caching")


# ---------------------------------------------------------------------------
# rng state (host-side; threaded functionally through compiled programs)
# ---------------------------------------------------------------------------

_rng_state: dict[str, Any] = {"key": None}


def enable_compilation_cache(directory: str, *, min_compile_secs: float = 1.0) -> None:
    """Persist XLA executables across processes (the reference's analog is
    nvFuser's ``ENABLE_NVFUSER_SERIALIZATION``; on TPU first-compiles run
    20-40s, so a warm on-disk cache removes them entirely). Honored
    automatically when ``THUNDER_TPU_COMPILATION_CACHE`` is set in the
    environment (read at import)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", str(directory))
    # jax initializes its persistent cache object once per process and then
    # ignores jax_compilation_cache_dir updates; reset so the new directory
    # takes effect even after earlier compiles in this process
    try:
        from jax.experimental.compilation_cache import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
    for opt in ("jax_persistent_cache_min_compile_time_secs",
                "jax_compilation_cache_min_compile_time_secs"):  # older spelling
        try:
            jax.config.update(opt, float(min_compile_secs))
            break
        except AttributeError:
            continue
    else:
        import warnings

        warnings.warn("could not set the persistent-cache compile-time threshold; "
                      "jax's default (1s) applies — sub-second compiles won't persist")
    # the kernel-quarantine set persists next to the cached executables: a
    # warm restart skips known-bad kernels BEFORE paying a doomed compile
    from thunder_tpu.runtime import quarantine as _rt_quarantine

    _rt_quarantine.configure(str(directory))
    # fitted cost-model constants persist there too: a warm restart applies
    # this platform's calibration overlay before the first verdict (every
    # affected decision records a typed ``calibrated[...]`` reason)
    from thunder_tpu.observe import calibrate as _obs_calibrate

    _obs_calibrate.configure(str(directory))


if _os.environ.get("THUNDER_TPU_COMPILATION_CACHE"):
    enable_compilation_cache(_os.environ["THUNDER_TPU_COMPILATION_CACHE"])


def manual_seed(seed: int) -> None:
    import jax

    _rng_state["key"] = jax.random.PRNGKey(seed)


def _next_rng_key():
    import jax

    if _rng_state["key"] is None:
        manual_seed(0)
    _rng_state["key"], sub = jax.random.split(_rng_state["key"])
    return sub


# ---------------------------------------------------------------------------
# compile data / stats
# ---------------------------------------------------------------------------

class CompileStats:
    def __init__(self):
        self.cache_hits = 0
        self.cache_misses = 0
        self.last_traces: list[TraceCtx] = []
        self.last_prologue_traces: list[TraceCtx] = []
        self.last_interpreted_ns = 0
        self.last_transform_ns = 0
        self.last_entry = None  # most recently compiled CacheEntry (for last_hlo)
        # observe subsystem: per-compile decision log (executor claims /
        # rejections, fusion accept/reject with cost-model inputs) and
        # per-pass walltimes (ms) — always collected, see thunder_tpu.observe
        self.last_decisions: list[dict] = []
        self.last_pass_times: dict[str, float] = {}
        # measured-time observatory: the last observe.profile.profile_window
        # result ({"profile": StepProfile, "ledger": [...], "summary": {...}})
        # — model-vs-measured residuals joined to last_decisions by region id
        self.last_profile = None
        self.fn_name = "fn"  # set by the owning ThunderTPUFunction
        # census knobs for this function's compiles (observe.census.ensure
        # reads them): the serving runner stashes its decode layer count +
        # launch budget here so the decode-launch-growth finding regenerates
        # on every census evaluation, not only at bind time
        self.census_context: dict = {}

    @property
    def last_census(self):
        """The executable census of the most recently compiled entry
        (``thunder_tpu.observe.census``): HLO collective instructions with
        ring-model recv bytes and async fractions (denominators included),
        kernel-launch / fusion-region counts, XLA cost/memory analysis, and
        the pessimization sentinel's findings. Lazy — the first access pays
        one memoized AOT compile of the entry (jax exposes no handle to the
        executable the run path built); never raises (census errors are
        counted and surfaced, not thrown). ``None`` before any compile."""
        from thunder_tpu.observe import census as _census

        return _census.ensure(self, fn_name=self.fn_name)

    @property
    def last_interpreted_ms(self) -> float:
        return self.last_interpreted_ns / 1e6

    @property
    def last_transform_ms(self) -> float:
        return self.last_transform_ns / 1e6

    def summary(self) -> str:
        """Human-readable compile-time breakdown of the last compilation.
        Pass times render hierarchically (sub-passes key as ``parent/child``
        in ``last_pass_times``): siblings at one level sum to their parent,
        so no line double-counts another."""
        lines = [
            f"cache: {self.cache_misses} miss(es), {self.cache_hits} hit(s)",
            f"tracing (interpretation): {self.last_interpreted_ms:.2f} ms",
            f"transforms + dispatch: {self.last_transform_ms:.2f} ms",
        ]

        def render(prefix: str, depth: int):
            level = {k: v for k, v in self.last_pass_times.items()
                     if k.startswith(prefix) and "/" not in k[len(prefix):]}
            for name, ms in sorted(level.items(), key=lambda kv: -kv[1]):
                lines.append(f"  {'  ' * depth}{name[len(prefix):]}: {ms:.2f} ms")
                render(name + "/", depth + 1)

        render("", 0)
        if self.last_decisions:
            lines.append(f"decisions recorded: {len(self.last_decisions)} "
                         f"(see thunder_tpu.observe.explain)")
        return "\n".join(lines)

    def __repr__(self):
        return f"<CompileStats\n{self.summary()}\n>"


class CacheEntry:
    __slots__ = ("computation_fn", "run_fn", "tensor_indices", "uses_rng", "traces",
                 "prologue_trace", "prologue_fn", "out_spec", "arg_of_flat",
                 "input_avals", "jit_obj", "is_sharded", "_examine_compiled",
                 "_examine_lowered", "census", "n_dev")

    def __init__(self, computation_fn, tensor_indices, uses_rng, traces, prologue_trace,
                 prologue_fn, out_spec):
        self.computation_fn = computation_fn
        self.run_fn = computation_fn  # may be wrapped (jit / shard_map) in finalize
        self.tensor_indices = tensor_indices
        self.uses_rng = uses_rng
        self.traces = traces
        self.prologue_trace = prologue_trace
        self.prologue_fn = prologue_fn
        self.out_spec = out_spec
        self.arg_of_flat: dict[int, int] | None = None  # flat index -> positional argnum
        self.input_avals = None  # jax.ShapeDtypeStructs of run_fn's inputs
        self.jit_obj = None      # the jax.jit object (lowerable), when one exists
        self.is_sharded = False  # True for shard_map-wrapped (distributed) entries
        # introspection caches: the ONE AOT lowering/executable every
        # consumer (census, last_hlo, examine.xla_memory/xla_cost) shares —
        # the no-recompile discipline lives in observe.census
        self._examine_lowered = None
        self._examine_compiled = None
        self.census = None       # memoized executable census (observe.census)
        self.n_dev = 1           # mesh size (distributed finalize overrides)


def _is_arraylike(x) -> bool:
    import jax

    return isinstance(x, (jax.Array, _np.ndarray)) or (
        hasattr(x, "shape") and hasattr(x, "dtype") and not isinstance(x, Proxy)
    )


def _leaf_key(leaf):
    if _is_arraylike(leaf):
        # the dtype OBJECT (numpy dtype / jax dtype) hashes and compares by
        # value; str(dtype) cost ~2x the whole key build on the decode hot
        # path (measured r4: 0.5 ms/call probing a 35-leaf tree)
        return ("T", tuple(leaf.shape), leaf.dtype)
    if isinstance(leaf, bool):
        return ("B", leaf)
    if isinstance(leaf, Number):
        return ("N", type(leaf).__name__, leaf)
    if isinstance(leaf, str):
        return ("S", leaf)
    if leaf is None:
        return ("Z",)
    return ("O", type(leaf).__name__)


class ThunderTPUFunction:
    """The compiled-function wrapper returned by ``thunder_tpu.jit``."""

    def __init__(self, fn: Callable, *, executors=None, cache: str = "constant values",
                 transforms: Sequence[Transform] = (), enable_cse: bool = True,
                 insert_dels: bool = True, sharp_edges: str = "allow",
                 fn_name: str | None = None, seq_buckets: Sequence[int] | None = None,
                 seq_argnums: Sequence[int] | None = None, seq_dim: int = -1,
                 **compile_options):
        from thunder_tpu.executors import resolve_executors

        check(cache in _CACHE_OPTIONS, lambda: f"unknown cache option {cache!r}")
        check(sharp_edges in ("allow", "warn", "error"),
              lambda: f"unknown sharp_edges option {sharp_edges!r}")
        self.sharp_edges = sharp_edges
        self.fn = fn
        self.executors = resolve_executors(executors)
        self.cache_option = cache
        self.transforms = list(transforms)
        self.enable_cse = enable_cse
        self.insert_dels = insert_dels
        self.fn_name = fn_name or getattr(fn, "__name__", "fn")
        self._cache: dict = {}
        self._stats = CompileStats()
        self._stats.fn_name = self.fn_name
        # Frontends may stash call-varying specialization context here (the
        # torch dialect's input-alias pattern: which args share a storage —
        # reference guards aliases via the prologue, thunder/__init__.py:
        # 357-375). It joins the cache key, so a call with aliased views
        # never hits an entry compiled for distinct tensors (and vice versa:
        # distinct tensors never re-trace an aliased specialization).
        # THREAD-LOCAL: each calling thread carries its own value, so
        # concurrent calls to one jitted fn never serialize or clobber each
        # other's specialization (advisor r4: the old shared field forced
        # callers to hold a lock across the whole execution).
        import threading as _threading

        self._call_tls = _threading.local()
        self.compile_options = dict(compile_options)
        self._compile_ctx = None  # last CompileContext (option usage report)
        self.__name__ = f"thunder_tpu.jit({self.fn_name})"
        # shape-polymorphic caching via bucketing (reference SYMBOLIC_VALUES
        # over shapes, thunder/core/proxies.py:624-1136 + options.py:95 —
        # on TPU the idiomatic answer is a fixed ladder of compiled lengths)
        self.seq_buckets = None
        self.seq_argnums = tuple(seq_argnums) if seq_argnums is not None else None
        self.seq_dim = seq_dim
        self._accepts_seq_len = False
        if seq_buckets is not None:
            from thunder_tpu.data import LengthBucketer

            self.seq_buckets = LengthBucketer(seq_buckets)
            import inspect

            # explicit `seq_len` parameter only — a VAR_KEYWORD catch-all
            # would misfire on forwarding wrappers (e.g. the torch-dialect
            # traced(*args, **kwargs) shim) and crash fns that don't take it
            try:
                self._accepts_seq_len = "seq_len" in inspect.signature(fn).parameters
            except (TypeError, ValueError):
                self._accepts_seq_len = False

    def _leaf_cache_key(self, leaf):
        # symbolic values: non-bool numbers become runtime inputs guarded by
        # type only (reference SYMBOLIC_VALUES, thunder/core/options.py:95) —
        # tensor SHAPES stay static: XLA compiles static programs, so shape
        # polymorphism on TPU is handled by data-pipeline bucketing
        # (thunder_tpu.data.LengthBucketer: pad to a small fixed ladder of
        # lengths, bounding compilations to the bucket count)
        if (self.cache_option == "symbolic values" and isinstance(leaf, Number)
                and not isinstance(leaf, bool)):
            return ("N", type(leaf).__name__)
        return _leaf_key(leaf)

    # -- bucketing ----------------------------------------------------------
    def _pad_to_bucket(self, args, kwargs):
        """Pad designated tensor leaves along ``seq_dim`` to the bucket ladder
        so distinct sequence lengths hit at most ``len(buckets)`` compiled
        programs. The TRUE length is passed to ``fn`` as a 0-d int32 array
        kwarg ``seq_len`` (when the signature accepts it) — a runtime tensor
        input, so masking sees the real length while the compiled shape stays
        the bucket's. Outputs keep the PADDED length: callers index them with
        the true length (or a mask), not ``[:, -1]``."""
        import jax.numpy as jnp
        import jax.tree_util as _jtu

        flat_paths, treedef = _jtu.tree_flatten_with_path((args, kwargs))
        flat = [leaf for _, leaf in flat_paths]
        designated = []
        for i, (path, leaf) in enumerate(flat_paths):
            if not _is_arraylike(leaf) or not getattr(leaf, "ndim", 0):
                continue
            if self.seq_argnums is not None:
                # path[0] selects args(0)/kwargs(1); path[1] the positional idx
                if len(path) < 2 or getattr(path[0], "idx", None) != 0:
                    continue
                if getattr(path[1], "idx", None) not in self.seq_argnums:
                    continue
            designated.append(i)
        check(designated, lambda: "seq_buckets is set but no tensor args were found")
        lengths = {int(flat[i].shape[self.seq_dim]) for i in designated}
        check(len(lengths) == 1, lambda: (
            f"seq_buckets: designated tensor args disagree on the sequence "
            f"dimension size ({sorted(lengths)}); pass seq_argnums to select "
            f"which positional args carry the sequence axis"))
        L = lengths.pop()
        Lb = self.seq_buckets.bucket_for(L)
        if Lb != L:
            new_flat = list(flat)
            for i in designated:
                leaf = flat[i]
                d = self.seq_dim % leaf.ndim
                widths = [(0, 0)] * leaf.ndim
                widths[d] = (0, Lb - L)
                new_flat[i] = jnp.pad(jnp.asarray(leaf), widths)
            args, kwargs = tree_unflatten(treedef, new_flat)
        if self._accepts_seq_len and "seq_len" not in kwargs:
            kwargs = dict(kwargs)
            kwargs["seq_len"] = _np.asarray(L, _np.int32)
        return args, kwargs

    # -- call ---------------------------------------------------------------
    def _entry_for(self, args, kwargs):
        """Single cache-lookup/compile path shared by __call__ and the
        compile-only entry point. Returns (entry, flat_inputs)."""
        if self.seq_buckets is not None:
            args, kwargs = self._pad_to_bucket(args, kwargs)
        flat, treedef = tree_flatten((args, kwargs))
        # the quarantine epoch joins the key (entries compiled before a
        # kernel was quarantined embed that kernel and must never hit
        # again), as does the context's bisection-suppression set (a probe
        # entry only serves calls under that same probe configuration), and
        # — only for plans with trace-time numerics:kernel specs — the
        # active FaultPlan's identity (that corruption is baked into the
        # executable, and must never serve after the plan is cleared;
        # grads/loss poison rides runtime inputs, so ordinary plans and the
        # production no-plan path add nothing to the key)
        plan = _faults.active_plan()
        plan_key = id(plan) if plan is not None and plan.affects_compile() \
            else None
        key = (treedef, self._extra_cache_key, _quarantine.epoch(),
               _quarantine.suppression_key(), plan_key,
               tuple(self._leaf_cache_key(l) for l in flat)) \
            if self.cache_option != "no caching" else None
        entry = self._cache.get(key) if key is not None else None
        if entry is None:
            self._stats.cache_misses += 1
            _observe.inc("cache.misses")
            _observe.event("cache_miss", fn=self.fn_name)
            entry = self._compile(flat, treedef, args, kwargs)
            if key is not None:
                self._cache[key] = entry
        else:
            self._stats.cache_hits += 1
            _observe.inc("cache.hits")
        return entry, flat

    def compile(self, *args, **kwargs) -> "CacheEntry":
        """Compile for these inputs WITHOUT executing (tooling entry point:
        ``examine`` and AOT-style inspection). Uses the same cache keying as
        ``__call__``, so a later call with the same shapes hits the entry."""
        entry, _ = self._entry_for(args, kwargs)
        return entry

    def __call__(self, *args, **kwargs):
        entry, flat = self._entry_for(args, kwargs)
        inps = [flat[i] for i in entry.tensor_indices]
        if entry.uses_rng:
            inps.append(_next_rng_key())
        return self._run_contained(entry.run_fn, inps, args, kwargs)

    def _run_contained(self, run_fn, inps, args, kwargs):
        """Run a compiled entry with the two containment paths armed: a
        claimed-kernel crash quarantines and recompiles; a sentinel
        silent-fault escalation bisects. Shared by ``__call__`` and the
        ``bind()`` fast path so the dispatch can never drift between them."""
        try:
            return run_fn(*inps)
        except KernelExecutionError as err:
            return self._quarantine_and_rerun(err, args, kwargs)
        except _sentinel.SilentNumericsFault as err:
            return self._bisect_and_rerun(err, args, kwargs)

    def _quarantine_and_rerun(self, err: KernelExecutionError, args, kwargs):
        """Graceful degradation: a claimed kernel died at compile or at
        runtime — quarantine that claim id, recompile the trace with the
        claim disabled (the op falls back to the XLA executor), and re-run.
        Loops in case a second claimed kernel fails on the recompiled
        program; a claim id seen twice means quarantining it didn't remove
        it from the program, so the error is real and propagates."""
        seen: set[str] = set()
        while True:
            if err.claim_id in seen:
                raise err
            seen.add(err.claim_id)
            _quarantine.get_quarantine().add(
                err.claim_id, reason=repr(err.__cause__ or err), phase=err.phase)
            _observe.inc("runtime.fallbacks")
            _observe.event("kernel_fallback", fn=self.fn_name, claim=err.claim_id,
                           phase=err.phase)
            # every cached entry may embed the quarantined kernel; the epoch
            # in the cache key already forces misses — drop the dead entries
            self._cache.clear()
            entry, flat = self._entry_for(args, kwargs)
            inps = [flat[i] for i in entry.tensor_indices]
            if entry.uses_rng:
                inps.append(_next_rng_key())
            try:
                return entry.run_fn(*inps)
            except KernelExecutionError as e2:
                err = e2
            except _sentinel.SilentNumericsFault as snf:
                # the crash was contained but another kernel is SILENTLY
                # corrupt: hand over to the bisection path (same symmetry as
                # __call__'s own dispatch between the two containments)
                return self._bisect_and_rerun(snf, args, kwargs)

    def _bisect_and_rerun(self, err, args, kwargs):
        """Silent-fault containment: the numerics sentinel saw repeated
        non-finite output at this trace point. Bisect the claimed custom
        kernels — recompile with candidate groups disabled
        (``runtime.quarantine.suppress``) and re-run on the same inputs —
        to attribute the corruption; the offender joins the PERSISTED
        quarantine (same path as crashing kernels) and the step re-runs on
        the XLA fallback. Unattributable corruption (still non-finite with
        every custom kernel disabled) re-raises as PersistentNonFinite for
        the supervisor's rewind/restart ladder."""
        guard = err.transform
        if guard is None:  # raised outside a guard wrapper: nothing to bisect
            raise err
        if not _sentinel.inputs_alive((args, kwargs)):
            # donate_argnums consumed the call's buffers in the failing
            # execution: probes cannot re-run these inputs. Escalate to the
            # supervisor ladder (rewind/restart from a checkpoint) instead
            # of crashing every probe on deleted arrays.
            raise _sentinel.PersistentNonFinite(
                f"persistent non-finite output of {self.fn_name}: the step's "
                f"inputs were donated (donate_argnums), so in-process "
                f"bisection cannot replay them — recover via the supervisor "
                f"(checkpoint restore + replay), or jit without donation to "
                f"enable bisection") from err
        sent = guard.sentinel
        seen: set[str] = set()
        # pin the RNG stream: every probe must run the SAME program on the
        # SAME inputs (probes differing only in the disabled set), and the
        # containment path must not advance the training stream — the final
        # re-run draws exactly the key a plain retry of this step would have
        rng_key0 = _rng_state["key"]
        while True:
            entry = err.entry if err.entry is not None else self._stats.last_entry
            exec_trc = entry.traces[-1] if entry is not None and entry.traces else None
            candidates = [] if exec_trc is None else \
                [c for c in _sentinel.claimed_kernel_ids(exec_trc) if c not in seen]
            if candidates:  # an empty set probes nothing: not a bisection run
                _observe.inc("runtime.bisections")
                _observe.event("bisection_started", fn=self.fn_name,
                               candidates=len(candidates))

            def probe(disabled):
                _rng_state["key"] = rng_key0
                with _quarantine.suppress(disabled):
                    self._cache.clear()
                    with sent.probing():
                        self(*args, **kwargs)
                return sent.last_verdict is not None and sent.last_verdict.healthy

            try:
                offenders = _sentinel.attribute_offenders(candidates, probe)
            finally:
                # a probe that raises (an active FaultPlan firing on a probe
                # recompile, an XLA error) must still unpin the RNG stream
                # and drop the probe-configuration entries
                self._cache.clear()
                _rng_state["key"] = rng_key0
            if not offenders:
                _observe.event("bisection_unattributed", fn=self.fn_name)
                raise _sentinel.PersistentNonFinite(
                    f"persistent non-finite output of {self.fn_name} could not "
                    f"be attributed to a claimed kernel "
                    f"({len(candidates)} candidates probed)") from err
            for offender in offenders:
                seen.add(offender)
                _quarantine.get_quarantine().add(
                    offender, phase="numerics",
                    reason=f"silent numerics fault attributed by bisection ({err})")
                _observe.inc("runtime.fallbacks")
                _observe.event("bisection_attributed", fn=self.fn_name,
                               claim=offender)
            sent.reset_episode()  # containment done: the re-run starts clean
            try:
                return self(*args, **kwargs)
            except _sentinel.SilentNumericsFault as e2:
                err = e2  # a second corrupt kernel: bisect the rest

    def bind(self, *args, **kwargs):
        """Compile for these inputs and return a ZERO-GUARD callable bound
        to that one cache entry — the serving fast path. A decode loop
        calling the jitted fn thousands of times per second pays the guard
        cache (flatten + per-leaf keys) on every call (~0.15 ms, measured
        r5 — ~4% of a 2-layer decode step); the bound callable skips it.
        The caller owns revalidation: invoking it with a different pytree
        structure, shapes, or dtypes than the binding inputs is undefined
        (reference analog: the reference hands back a compiled
        ``CompiledFunction`` the same way, thunder/__init__.py jit).

        Containment still applies: a claimed-kernel crash or a sentinel
        silent-fault escalation re-enters the driver's quarantine/bisection
        path with the call's own arguments — but the containment recompiles
        under a NEW cache entry, so after it fires the caller should
        re-``bind`` (the stale bound entry would re-contain every call)."""
        check(self.seq_buckets is None,
              "bind() does not compose with seq_buckets: the bound callable "
              "skips the guard path that pads inputs to the bucket. For "
              "ragged-length serving use thunder_tpu.serving.ServingEngine "
              "— its scheduler owns the bucketing (LengthBucketer prefill "
              "chunks) and binds a fixed-shape decode step. Otherwise call "
              "the jitted function directly, or bind a fn without buckets")
        entry, _ = self._entry_for(args, kwargs)
        tensor_indices = entry.tensor_indices
        uses_rng = entry.uses_rng
        run_fn = entry.run_fn

        def bound(*a, **k):
            fl, _ = tree_flatten((a, k))
            inps = [fl[i] for i in tensor_indices]
            if uses_rng:
                inps.append(_next_rng_key())
            return self._run_contained(run_fn, inps, a, k)

        bound.entry = entry
        return bound

    # -- compilation --------------------------------------------------------
    def _trace(self, flat, treedef) -> tuple[TraceCtx, list[int]]:
        trc = TraceCtx("computation")
        tensor_indices: list[int] = []
        with tracectx(trc):
            proxies = []
            symbolic_numbers = self.cache_option == "symbolic values"
            for i, leaf in enumerate(flat):
                if _is_arraylike(leaf):
                    p = self._make_input_proxy(i, leaf)
                    proxies.append(p)
                    tensor_indices.append(i)
                elif (symbolic_numbers and isinstance(leaf, Number)
                      and not isinstance(leaf, bool)):
                    p = NumberProxy(leaf)  # value is a runtime input, not baked
                    proxies.append(p)
                    tensor_indices.append(i)
                else:
                    proxies.append(leaf)  # constant-values caching: baked + guarded
            pargs, pkwargs = tree_unflatten(treedef, proxies)
            result = self.fn(*pargs, **pkwargs)
            prims.python_return(result)
        trc.args = [proxies[i] for i in tensor_indices]
        trc.output = result
        if getattr(trc, "rng_input_proxy", None) is not None:
            trc.args.append(trc.rng_input_proxy)
        # the full (proxy-for-every-leaf) input structure, for transforms
        # that need to map positional args to their proxies (the numerics
        # guard pairs state args with state outputs through this)
        trc.input_proxies = list(proxies)
        trc.input_treedef = treedef
        trc.set_provenance("Tracing (duck-typed interpretation)")
        return trc, tensor_indices

    def _build_prologue(self, flat, tensor_indices) -> TraceCtx:
        pro = TraceCtx("prologue")
        with tracectx(pro):
            pro_proxies = []
            returns = []
            for i, leaf in enumerate(flat):
                if _is_arraylike(leaf):
                    p = TensorProxy(f"arg{i}", shape=leaf.shape, dtype=dtypes.to_dtype(leaf.dtype))
                    prims.check_tensor_shape_and_metadata(p, tuple(p.shape), p.dtype, str(p.device))
                    returns.append(p)
                elif isinstance(leaf, Number):
                    p = NumberProxy(leaf, f"arg{i}")
                    if self.cache_option == "symbolic values" and not isinstance(leaf, bool):
                        prims.check_number_type(p, type(leaf).__name__)
                        returns.append(p)
                    else:
                        prims.check_number_type_and_value(p, leaf)
                elif isinstance(leaf, str):
                    p = StringProxy(leaf, f"arg{i}")
                    prims.check_string_value(p, leaf)
                else:
                    p = NumberProxy(0, f"arg{i}", python_type=type(leaf))
                    prims.check_literal_like(p, leaf)
                pro_proxies.append(p)
            prims.python_return(tuple(returns))
        pro.args = pro_proxies
        pro.output = tuple(returns)
        pro.set_provenance("Prologue (input guards)")
        return pro

    def _compile(self, flat, treedef, args, kwargs) -> CacheEntry:
        from thunder_tpu.core.compile_data import CompileContext, compile_context

        self._compile_ctx = CompileContext(self.compile_options,
                                           executors=self.executors)
        with compile_context(self._compile_ctx):
            return self._compile_inner(flat, treedef, args, kwargs)

    def _compile_inner(self, flat, treedef, args, kwargs) -> CacheEntry:
        from thunder_tpu.observe import decisions as _decisions

        _faults.maybe_fail("compile", site=self.fn_name)
        # collect locally, install into stats only on success: a failed
        # recompile must not leave explain()/summary() mixing this compile's
        # partial decisions/pass-times with the previous compile's traces
        pass_times: dict[str, float] = {}
        with _observe.collect_pass_times(pass_times), \
                _decisions.collect() as decision_log, \
                _observe.span("compile", args={"fn": self.fn_name},
                              record_pass_time=False):
            entry = self._compile_instrumented(flat, treedef, args, kwargs)
        self._stats.last_pass_times = pass_times
        self._stats.last_decisions = decision_log
        _observe.inc("compile.count")
        _observe.set_gauge("compile.interpreted_ms", self._stats.last_interpreted_ms)
        _observe.set_gauge("compile.transform_ms", self._stats.last_transform_ms)
        return entry

    def _compile_instrumented(self, flat, treedef, args, kwargs) -> CacheEntry:
        from thunder_tpu.executors.passes import del_last_used, transform_for_execution
        from thunder_tpu.observe import runtime as _obs_runtime

        t0 = time.perf_counter_ns()
        with _observe.span("trace"):
            trc, tensor_indices = self._trace(flat, treedef)
        self._stats.last_interpreted_ns = time.perf_counter_ns() - t0
        if trc.sharp_edges and self.sharp_edges != "allow":
            msg = "sharp edges detected during tracing (reference SHARP_EDGES_OPTIONS):\n  " \
                  + "\n  ".join(trc.sharp_edges)
            if self.sharp_edges == "error":
                raise RuntimeError(msg)
            import warnings

            warnings.warn(msg, stacklevel=3)
        traces = [trc]

        t1 = time.perf_counter_ns()
        with _observe.span("prologue"):
            prologue = self._build_prologue(flat, tensor_indices)
            for tr in self.transforms:
                _, trc, _ = tr.transform_traces_pre_prologue(prologue, trc, None)

        with _observe.span("dce+cse"):
            trc = dce(trc)
            traces.append(trc)
            if self.enable_cse:
                trc = cse(trc)
                trc = dce(trc)
                traces.append(trc)

        with _observe.span("transform_for_execution"):
            exec_trc = transform_for_execution(trc, self.executors)
        # the claim-level region-annotated trace (observe.profile replays it
        # per region on backends without a profiler) rides in entry.traces so
        # it survives the post-optimization transforms below, which rebuild
        # the execution trace and would drop the attribute
        region_trc = getattr(exec_trc, "_region_trace", None)
        if region_trc is not None:
            traces.append(region_trc)
        for tr in self.transforms:
            exec_trc = tr.transform_trace_post_optimization(exec_trc)
        if self.insert_dels:
            with _observe.span("del_last_used"):
                exec_trc = del_last_used(exec_trc)
        traces.append(exec_trc)
        self._stats.last_transform_ns = time.perf_counter_ns() - t1

        from thunder_tpu.core.compile_data import get_compile_option

        execution_file = get_compile_option(
            "execution_file",
            "dump the final generated program to this file — or, if the file "
            "already exists (user-edited), execute its contents instead "
            "(reference set_execution_callback_file: hand-patch generated code)",
            None)
        with _observe.span("codegen"):
            computation_fn = exec_trc.python_callable(execution_file=execution_file)
            prologue_fn = prologue.python_callable()
        # sanity-run the prologue guards once on the compiling inputs
        prologue_fn(*flat)

        uses_rng = getattr(traces[0], "rng_input_proxy", None) is not None
        entry = CacheEntry(computation_fn, tensor_indices, uses_rng, traces, prologue,
                           prologue_fn, None)
        # map flat leaf positions to top-level positional args (donation support)
        import jax.tree_util as _jtu

        flat_with_paths, _ = _jtu.tree_flatten_with_path((args, kwargs))
        entry.arg_of_flat = {}
        for i, (path, _leaf) in enumerate(flat_with_paths):
            if len(path) >= 2 and getattr(path[0], "idx", None) == 0:
                entry.arg_of_flat[i] = getattr(path[1], "idx", None)
        import jax as _jax

        def _leaf_aval(leaf):
            # GSPMD inputs: a leaf committed to a NamedSharding over >1 device
            # must carry that sharding into the aval, or census lowering
            # (`jit_obj.lower(*input_avals)`) would compile an unsharded
            # program and miss every collective the real step executes
            aval = _jax.ShapeDtypeStruct(
                tuple(leaf.shape), dtypes.to_dtype(leaf.dtype).jax)
            sh = getattr(leaf, "sharding", None)
            if (isinstance(sh, _jax.sharding.NamedSharding)
                    and sh.mesh.size > 1 and getattr(leaf, "_committed", True)):
                aval = _jax.ShapeDtypeStruct(aval.shape, aval.dtype, sharding=sh)
            return aval

        if all(hasattr(flat[i], "shape") for i in tensor_indices):
            entry.input_avals = [_leaf_aval(flat[i]) for i in tensor_indices]
            if uses_rng:
                entry.input_avals.append(_jax.ShapeDtypeStruct((2,), _np.uint32))
            # transforms may thread extra runtime inputs into the trace
            # signature (the numerics guard's poison scalars)
            for tr in self.transforms:
                extra = getattr(tr, "extra_input_avals", None)
                if extra is not None:
                    entry.input_avals.extend(extra())
        # else (symbolic-values caching: number inputs): no avals — last_hlo
        # reports accordingly
        with _observe.span("finalize"):
            self._finalize_entry(entry, flat, exec_trc)
        # runtime step metrics: one disabled-check per call when observe is
        # off, walltime/span/memory-estimate recording when on
        entry.run_fn = _obs_runtime.instrument_entry(entry, self.fn_name)
        # transform runtime wrappers (outermost): the numerics guard feeds
        # its poison inputs and peels the health word here. REVERSED so the
        # first transform's wrapper ends up outermost — wrappers append
        # their extra inputs outermost-first, which must match the order
        # the transforms appended their proxies to the trace signature
        # (and extra_input_avals / the distributed in_specs extension)
        for tr in reversed(self.transforms):
            hook = getattr(tr, "wrap_run_fn", None)
            if hook is not None:
                entry.run_fn = hook(self, entry, entry.run_fn)
        self._stats.last_traces = traces
        self._stats.last_prologue_traces = [prologue]
        self._stats.last_entry = entry
        return entry

    # -- subclass hooks (distributed wrappers override these) ---------------
    def _make_input_proxy(self, i: int, leaf) -> TensorProxy:
        return TensorProxy(shape=leaf.shape, dtype=dtypes.to_dtype(leaf.dtype))

    def _finalize_entry(self, entry: CacheEntry, flat, exec_trc) -> None:
        """Whole-program compilation: the generated trace callable is pure JAX
        ops, so one ``jax.jit`` over it gives XLA whole-program fusion and a
        persistent executable — the TPU answer to the reference's CUDA-graphs
        executor (``thunder/executors/cudagraphex.py:133``: capture once,
        replay with stable buffers). Region fusions inline into the outer jit.

        ``donate_argnums=(i, ...)`` (a jit compile option, matching jax.jit's
        parameter): tensor leaves under those positional args are donated so
        XLA reuses their buffers for outputs — in-place optimizer updates.
        """
        if self.cache_option == "symbolic values":
            # number inputs are Python scalars guarded by type; an outer jit
            # would re-trace per value, defeating symbolic caching — keep the
            # per-region execution path
            return
        from thunder_tpu.core.compile_data import get_compile_option

        if not get_compile_option(
                "whole_program_jit",
                "compile the entire execution trace as one XLA program "
                "(persistent executable; CUDA-graphs analog)", True):
            return
        # host-sync ops (item etc.) need concrete values — they cannot live
        # under an outer jit; keep the per-region path (regions stay compiled,
        # sync ops run eagerly between them)
        from thunder_tpu.core.prims import OpTags as _OpTags

        for b in exec_trc.bound_symbols:
            if _OpTags.DEVICE_SYNC_OP in b.sym.tags:
                return
        import jax

        donate_args = tuple(get_compile_option(
            "donate_argnums",
            "positional args whose tensor leaves are donated to XLA "
            "(buffer reuse for outputs; pass params/optimizer-state argnums)",
            ()) or ())
        donate = ()
        if donate_args and entry.arg_of_flat is not None:
            donate = tuple(
                j for j, fi in enumerate(entry.tensor_indices)
                if entry.arg_of_flat.get(fi) in donate_args)
        entry.run_fn = jax.jit(entry.computation_fn, donate_argnums=donate)
        entry.jit_obj = entry.run_fn
        # GSPMD: when any input is committed to a multi-device mesh the jit
        # compiles one SPMD program over it — record the device count so the
        # census ring model and budget gates divide by the right n
        for leaf in flat:
            sh = getattr(leaf, "sharding", None)
            if (isinstance(sh, jax.sharding.NamedSharding)
                    and sh.mesh.size > getattr(entry, "n_dev", 1)):
                entry.n_dev = sh.mesh.size

    @property
    def _extra_cache_key(self):
        return getattr(self._call_tls, "extra_cache_key", None)

    @_extra_cache_key.setter
    def _extra_cache_key(self, value):
        self._call_tls.extra_cache_key = value

    # -- introspection ------------------------------------------------------
    @property
    def cache_hits(self):
        return self._stats.cache_hits

    @property
    def cache_misses(self):
        return self._stats.cache_misses


def jit(fn: Callable | None = None, *, executors=None, cache: str = "constant values",
        transforms: Sequence[Transform] = (), enable_cse: bool = True,
        insert_dels: bool = True, sharp_edges: str = "allow",
        seq_buckets: Sequence[int] | None = None,
        seq_argnums: Sequence[int] | None = None, seq_dim: int = -1,
        **compile_options) -> ThunderTPUFunction:
    """Compile ``fn``: trace → transform → dispatch to executors.

    ``seq_buckets=(256, 512, ...)`` enables shape-polymorphic caching by
    bucketing: on each call, tensor args (all of them, or those selected by
    ``seq_argnums``) are zero-padded along ``seq_dim`` to the next ladder
    length, bounding compilations to the ladder size; the true length is
    passed as a 0-d ``seq_len`` tensor when ``fn`` accepts it, so masking
    stays exact (the TPU answer to the reference's symbolic-shape caching,
    ``thunder/core/proxies.py:624-1136``, ``thunder/core/options.py:95``).
    Outputs keep the PADDED length — index them with the true length or a
    mask (``logits[:, -1]`` would read a pad position).

    Free-form ``**compile_options`` are queried lazily by passes/executors via
    ``thunder_tpu.core.compile_data.get_compile_option``; see
    ``last_compile_options`` for the used/unused report.

    Reference: ``thunder.jit`` (``thunder/__init__.py:262``).
    """
    shape_opts = dict(seq_buckets=seq_buckets, seq_argnums=seq_argnums, seq_dim=seq_dim)
    if fn is None:
        def deco(f):
            return jit(f, executors=executors, cache=cache, transforms=transforms,
                       enable_cse=enable_cse, insert_dels=insert_dels,
                       sharp_edges=sharp_edges, **shape_opts, **compile_options)

        return deco
    import sys

    _torch = sys.modules.get("torch")
    if _torch is not None and isinstance(fn, _torch.nn.Module):
        from thunder_tpu.torch import jit as torch_jit

        return torch_jit(fn, executors=executors, cache=cache, transforms=transforms,
                         enable_cse=enable_cse, insert_dels=insert_dels,
                         sharp_edges=sharp_edges, **shape_opts, **compile_options)
    return ThunderTPUFunction(fn, executors=executors, cache=cache, transforms=transforms,
                              enable_cse=enable_cse, insert_dels=insert_dels,
                              sharp_edges=sharp_edges, **shape_opts, **compile_options)


# ---------------------------------------------------------------------------
# autograd entry points
# ---------------------------------------------------------------------------

def value_and_grad(fn: Callable, argnums=0, has_aux: bool = False) -> Callable:
    """Trace-level VJP of ``fn``; usable inside a jitted function (inlines
    forward+backward into the enclosing trace)."""
    return inline_value_and_grad(fn, argnums=argnums, has_aux=has_aux)


def grad(fn: Callable, argnums=0) -> Callable:
    vag = inline_value_and_grad(fn, argnums=argnums)

    def grad_fn(*args, **kwargs):
        _, g = vag(*args, **kwargs)
        return g

    return grad_fn


def jvp(fn: Callable) -> Callable:
    """Forward-mode derivative: jvp(fn)(primals, tangents) -> (out, out_tangent).
    Usable inside a jitted function (reference ``transforms.py:2175``)."""

    def jvp_fn(primals, tangents):
        return jvp_call(fn, tuple(primals), tuple(tangents))

    return jvp_fn


def _vmap_impl(fn: Callable, in_axes=0) -> Callable:
    """Trace-level vmap (per-prim batching rules, composable with grad and
    executor claiming — reference ``thunder/core/transforms.py:1902``), with
    automatic fallback to the opaque jax.vmap lowering for ops without rules."""

    def wrapper(*args):
        from thunder_tpu.core.batching import NoBatchRule, inline_vmap
        from thunder_tpu.core.trace import get_tracectx

        trc = get_tracectx()
        mark = len(trc.bound_symbols) if trc is not None else 0
        try:
            return inline_vmap(fn, in_axes)(*args)
        except NoBatchRule:
            if trc is not None:  # roll back partially-emitted batched ops
                del trc.bound_symbols[mark:]
            return vmap_call(fn, in_axes=in_axes)(*args)

    return wrapper


def vmap(fn: Callable, in_axes=0) -> Callable:
    """Batching transform (reference ``transforms.py:1902``): trace-level
    per-prim batching rules — the output is ordinary trace IR, so it composes
    with ``tt.grad`` and executor claiming (a vmapped SDPA is still claimed
    by Pallas). Ops without a rule fall back per-call to the opaque jax.vmap
    lowering."""
    return _vmap_impl(fn, in_axes=in_axes)


# ---------------------------------------------------------------------------
# introspection (reference thunder/__init__.py:859-944)
# ---------------------------------------------------------------------------

def _as_tfn(x) -> ThunderTPUFunction:
    check(isinstance(x, ThunderTPUFunction), "expected a thunder_tpu.jit-compiled function")
    return x


def last_traces(jfn) -> list[TraceCtx]:
    return _as_tfn(jfn)._stats.last_traces


def last_execution_trace(jfn) -> TraceCtx:
    return _as_tfn(jfn)._stats.last_traces[-1]


def last_prologue_traces(jfn) -> list[TraceCtx]:
    return _as_tfn(jfn)._stats.last_prologue_traces


def cache_hits(jfn) -> int:
    return _as_tfn(jfn)._stats.cache_hits


def cache_misses(jfn) -> int:
    return _as_tfn(jfn)._stats.cache_misses


def compile_stats(jfn) -> CompileStats:
    return _as_tfn(jfn)._stats


def last_hlo(jfn, *, optimized: bool = False) -> str:
    """StableHLO (or XLA-optimized HLO with ``optimized=True``) of the most
    recently compiled entry — the per-stage dump SURVEY §7 calls out as the
    multi-host debugging essential (the trace prints Python; this is what XLA
    actually receives/produces).

    Both stages are memoized per entry through ``observe.census``'s shared
    accessors: ``optimized=True`` used to pay a FULL second XLA compile via
    ``lowered.compile()`` on every call — now the first caller (here, the
    census, or ``examine.xla_memory/xla_cost``) builds the one AOT
    executable and everyone after reuses it."""
    from thunder_tpu.observe import census as _census

    entry = _as_tfn(jfn)._stats.last_entry
    check(entry is not None, "no compilation has run yet")
    check(entry.input_avals is not None,
          "entry has no recorded input shapes (symbolic-values caching)")
    check(entry.jit_obj is not None,
          "entry is not whole-program-jitted (device-sync ops in the trace or "
          "whole_program_jit=False); no HLO available")
    if optimized:
        return _census.compiled_for_entry(entry).as_text()
    return _census.lowered_for_entry(entry).as_text()


def hlo_census(jfn) -> dict | None:
    """The per-compile executable census of ``jfn``'s most recent entry —
    ``CompileStats.last_census`` as a function (see
    ``thunder_tpu.observe.census`` for the dict shape and the
    pessimization-sentinel findings it carries)."""
    return _as_tfn(jfn)._stats.last_census


def last_jaxpr(jfn):
    """Closed jaxpr of the most recently compiled entry's computation.
    Single-program entries only — a distributed entry's computation runs
    per-shard inside shard_map (its collectives are unbound outside it);
    use ``last_hlo`` there."""
    import jax

    entry = _as_tfn(jfn)._stats.last_entry
    check(entry is not None, "no compilation has run yet")
    check(entry.input_avals is not None,
          "entry has no recorded input shapes (symbolic-values caching)")
    check(not getattr(entry, "is_sharded", False),
          "distributed entries run per-shard inside shard_map — the jaxpr of "
          "the local computation is not well-formed standalone; use last_hlo")
    return jax.make_jaxpr(entry.computation_fn)(*entry.input_avals)


def last_compile_options(jfn) -> str:
    """Report which compile options the last compilation queried (with their
    self-registered descriptions) and which passed options were never used
    (reference ``thunder/__init__.py:980-1015``)."""
    from thunder_tpu.core.compile_data import used_and_unused_options

    ctx = _as_tfn(jfn)._compile_ctx
    if ctx is None:
        return "no compilation has run yet"
    queried, unused = used_and_unused_options(ctx)
    lines = ["queried compile options:"]
    for name, desc in sorted(queried.items()):
        mark = "set" if name in ctx.options else "default"
        lines.append(f"  {name} [{mark}]: {desc}")
    if unused:
        lines.append("passed but never queried (possibly misspelled):")
        for name in sorted(unused):
            lines.append(f"  {name}")
    return "\n".join(lines)


# re-exports
from thunder_tpu import ops  # noqa: E402,F401
from thunder_tpu.ops import autocast  # noqa: E402,F401
from thunder_tpu.executors import (  # noqa: E402,F401
    get_all_executors,
    get_default_executors,
    get_executor,
)
from thunder_tpu import serving  # noqa: E402,F401  (thunder_tpu.serving.*)

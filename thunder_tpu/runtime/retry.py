"""Retry/timeout/backoff policy engine.

Per-domain policies with jittered exponential backoff and deadline budgets,
plus the exception classifier that decides what a failure *means*:

- ``retryable`` — transient (device error, preemption, injected transient
  fault): back off and try again.
- ``fatal`` — never retry (``KeyboardInterrupt``, programming errors);
  re-raise immediately.
- ``degradable`` — the failure names a component that can be disabled
  (:class:`~thunder_tpu.runtime.faults.KernelExecutionError` carries a claim
  id): quarantine it and recompile rather than retrying the same program.

:class:`RestartBudget` is the sliding-window restart counter the supervisor
uses instead of a per-lifetime cap — a job that fails once a day for a week
is healthy; one that fails five times in ten minutes is not.
"""

from __future__ import annotations

import random
import time
from collections import deque
from typing import Callable

from thunder_tpu.observe import registry as _observe
from thunder_tpu.runtime.faults import InjectedFault, KernelExecutionError

RETRYABLE = "retryable"
FATAL = "fatal"
DEGRADABLE = "degradable"


def classify(exc: BaseException) -> str:
    """Default exception classifier (override per call site as needed)."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return FATAL
    if isinstance(exc, KernelExecutionError):
        return DEGRADABLE
    if isinstance(exc, InjectedFault):
        return RETRYABLE
    # XlaRuntimeError lives in jaxlib; match by name so environments without
    # the extension (or with a moved module path) still classify correctly
    if any(c.__name__ == "XlaRuntimeError" for c in type(exc).__mro__):
        return RETRYABLE
    if isinstance(exc, (OSError, RuntimeError)):
        return RETRYABLE
    return FATAL


class RetryPolicy:
    """Jittered exponential backoff with an optional deadline budget.

    ``delay_s(attempt)`` is deterministic for a given ``seed``:
    ``base * multiplier**(attempt-1)`` capped at ``max_delay_s``, scaled by
    a uniform jitter in ``[1-jitter, 1+jitter]``.
    """

    def __init__(self, *, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 5.0, multiplier: float = 2.0,
                 jitter: float = 0.25, deadline_s: float | None = None,
                 seed: int = 0):
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline_s = deadline_s
        self._rng = random.Random(seed)

    def delay_s(self, attempt: int) -> float:
        d = min(self.base_delay_s * self.multiplier ** max(attempt - 1, 0),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(d, 0.0)


# per-domain defaults: compiles are expensive (few, patient attempts);
# dispatch/collective failures are cheap to retry; checkpoint IO sits between
DEFAULT_POLICIES: dict[str, RetryPolicy] = {
    "compile": RetryPolicy(max_attempts=2, base_delay_s=1.0, max_delay_s=30.0),
    "dispatch": RetryPolicy(max_attempts=3, base_delay_s=0.05),
    "collective": RetryPolicy(max_attempts=3, base_delay_s=0.2, max_delay_s=10.0),
    "checkpoint_io": RetryPolicy(max_attempts=4, base_delay_s=0.5, max_delay_s=30.0),
    "step": RetryPolicy(max_attempts=3, base_delay_s=0.5, max_delay_s=60.0),
}


def policy_for(domain: str) -> RetryPolicy:
    return DEFAULT_POLICIES.get(domain, RetryPolicy())


def call_with_retry(fn: Callable, *args, policy: RetryPolicy | None = None,
                    domain: str = "", classify_fn: Callable = classify,
                    sleep: Callable[[float], None] = time.sleep,
                    clock: Callable[[], float] = time.monotonic,
                    on_retry: Callable | None = None, **kwargs):
    """Run ``fn`` under ``policy``. Retries ``retryable`` failures with
    backoff until attempts or the deadline budget run out; ``fatal`` and
    ``degradable`` failures propagate immediately (degradation is the
    dispatch layer's job, not a blind re-run's)."""
    policy = policy or (policy_for(domain) if domain else RetryPolicy())
    start = clock()
    failures = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if classify_fn(e) != RETRYABLE:
                raise
            failures += 1
            if failures >= policy.max_attempts:
                raise
            d = policy.delay_s(failures)
            if policy.deadline_s is not None and \
                    clock() - start + d > policy.deadline_s:
                raise
            _observe.inc("runtime.retries")
            _observe.observe_value("runtime.backoff_ms", d * 1e3)
            _observe.event("retry", domain=domain, attempt=failures,
                           delay_s=d, error=repr(e))
            if on_retry is not None:
                on_retry(failures, d, e)
            sleep(d)


class RestartBudget:
    """Sliding-window restart counter: at most ``max_restarts`` restarts per
    ``window_s`` seconds (``None`` = lifetime window, the legacy behavior).

    ``record()`` logs one restart and returns whether the budget still
    allows it; old restarts age out of the window, so a long-lived job is
    judged by its recent stability, not its history."""

    def __init__(self, max_restarts: int = 3, window_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._clock = clock
        self._events: deque[float] = deque()

    def _prune(self, now: float) -> None:
        if self.window_s is None:
            return
        while self._events and now - self._events[0] > self.window_s:
            self._events.popleft()

    def record(self) -> bool:
        now = self._clock()
        self._events.append(now)
        self._prune(now)
        return len(self._events) <= self.max_restarts

    @property
    def in_window(self) -> int:
        self._prune(self._clock())
        return len(self._events)

    def describe(self) -> str:
        """One-line budget state for events/reports:
        ``2/3 restarts in 600s window``."""
        window = "lifetime" if self.window_s is None else f"{self.window_s:g}s"
        return f"{self.in_window}/{self.max_restarts} restarts in {window} window"

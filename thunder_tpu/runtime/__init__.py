"""thunder_tpu.runtime: the fault-domain runtime.

Production hardening for the compile/dispatch stack (ROADMAP item 5,
SURVEY §5 "Failure detection / elastic recovery: Absent" in the reference):

- ``faults``: layered fault injection — a :class:`FaultPlan` names injection
  *domains* (``compile``, ``dispatch``, ``kernel:<claim>``, ``collective``,
  ``checkpoint_io``, ``step``) with deterministic schedules (step sets,
  every-N, seeded probability) and transient-vs-permanent semantics. Hook
  points are threaded through ``_compile_inner``, the ``CacheEntry.run_fn``
  wrapper, every ``register_operator`` claim impl (the Pallas kernels), the
  distributed collective lowerings, and ``checkpoint.save_checkpoint``.
- ``retry``: per-domain retry/timeout/backoff policies — jittered
  exponential backoff, deadline budgets, a sliding-window
  :class:`RestartBudget`, and an exception classifier
  (retryable / fatal / degradable).
- ``quarantine``: when a claimed kernel fails at compile or at runtime the
  dispatch layer quarantines that claim id, recompiles the trace with the
  claim disabled (the op falls back to the XLA executor), and persists the
  quarantine set next to the persistent compile cache so restarts skip the
  known-bad kernel. Every fallback lands in ``CompileStats.last_decisions``
  (visible in ``observe.explain()``) and the ``runtime.fallbacks`` counter.

- ``sentinel``: the numerical-integrity side of the fault taxonomy — silent
  data faults (NaN/Inf grads, loss spikes, numerically corrupt claimed
  kernels) detected by in-graph health reductions
  (``thunder_tpu.transforms.NumericsGuardTransform``), skipped in-graph
  with bit-identical state, and escalated through a response ladder:
  skip-and-count → EWMA loss-spike rewind → automated bisection that
  attributes the corruption to one claimed kernel and feeds it into the
  persisted quarantine.

The supervisor side (SIGTERM-aware checkpoint-and-exit, restart backoff,
heartbeat watchdog, ``numerics_policy=`` rewind wiring) lives in
``thunder_tpu.elastic`` on top of these.
"""

from __future__ import annotations

from thunder_tpu.runtime import faults, quarantine, retry, sentinel  # noqa: F401
from thunder_tpu.runtime.faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    InjectedFault,
    KernelExecutionError,
)
from thunder_tpu.runtime.retry import RestartBudget, RetryPolicy  # noqa: F401
from thunder_tpu.runtime.sentinel import (  # noqa: F401
    LossSpike,
    NumericsAnomaly,
    NumericsPolicy,
    NumericsSentinel,
    PersistentNonFinite,
    SilentNumericsFault,
)

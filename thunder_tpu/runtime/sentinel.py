"""Numerical integrity sentinel: the host side of in-graph NaN/spike defense.

PR 7 made thunder_tpu survive failures that *raise*; the worse production
failure mode is silent — NaN/Inf gradients, loss spikes, a numerically
corrupt claimed kernel returning garbage without an exception — poisoning
the model until someone eyeballs the loss curve. The defense has two halves:

- **In-graph** (``thunder_tpu.transforms.NumericsGuardTransform``): every
  compiled training step gets cheap fused health reductions — global grad
  norm plus non-finite counts over grads/loss/new-state, packed into one
  small f32 *health word* — and emits ``where(healthy, new_state, old_state)``
  so a non-finite step is *skipped* with bit-identical state and no host
  round-trip. Detection costs one health-word fetch per step.
- **Host-side** (this module): :class:`NumericsSentinel` consumes the health
  word per step and drives the response ladder of :class:`NumericsPolicy`:

  1. *skip-and-count* — a transient non-finite step was already skipped
     in-graph; the sentinel counts it (``runtime.nonfinite_steps`` /
     ``runtime.skipped_steps``) and moves on,
  2. *rewind* — a finite loss that spikes against its EWMA (z-score over
     ``spike_zscore``) raises :class:`LossSpike`; ``ElasticTrainer``
     (``numerics_policy=``) classifies it retryable, restores the last
     committed checkpoint and replays in data order (``runtime.rewinds``),
  3. *bisect* — ``bisect_after`` consecutive non-finite steps at the same
     trace point raise :class:`SilentNumericsFault`; the jit driver runs
     :func:`bisect_offender` — recompiling with claimed kernel groups
     disabled (``runtime.quarantine.suppress``) — and feeds the attributed
     claim id into the persisted kernel quarantine, so silent faults reach
     the same quarantine + decision-log path as crashes
     (``runtime.bisections`` / ``runtime.bisection_probes``).

Every anomaly can dump a *replay bundle* (trace hash, step inputs, RNG
state, decision log) for offline repro: set ``NumericsPolicy.replay_dir``.

Chaos-test the whole ladder with the ``numerics:*`` fault domains of
``runtime.faults.FaultPlan`` (``numerics:grads``, ``numerics:loss``,
``numerics:kernel:<claim>``).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import time
import weakref
from contextlib import contextmanager

from thunder_tpu.observe import registry as _observe


class NumericsAnomaly(RuntimeError):
    """Base for sentinel-detected anomalies (classified retryable)."""


class LossSpike(NumericsAnomaly):
    """Finite loss spiked against its EWMA: rewind to the last committed
    checkpoint and replay in data order."""

    def __init__(self, *, step: int, loss: float, ewma: float, z: float):
        super().__init__(f"loss spike at sentinel step {step}: loss={loss:.6g} "
                         f"vs ewma={ewma:.6g} (z={z:.2f})")
        self.step = step
        self.loss = loss
        self.ewma = ewma
        self.z = z
        self.sentinel = None  # set by the raising NumericsSentinel so the
        # supervisor can notify_rewind() with the replay length


class PersistentNonFinite(NumericsAnomaly):
    """Non-finite steps persisted and bisection could not attribute them to
    a claimed kernel (or was disabled): the corruption is upstream of the
    custom kernels (model divergence, data poisoning, chip fault)."""


class SilentNumericsFault(NumericsAnomaly):
    """Internal control flow: repeated non-finite at one trace point — the
    jit driver catches this and runs the bisection (it holds the original
    call arguments needed to recompile and re-run probes)."""

    def __init__(self, verdict: "Verdict", message: str = ""):
        super().__init__(message or f"persistent non-finite step: {verdict}")
        self.verdict = verdict
        self.transform = None  # set by the guard wrapper (bisection needs it)
        self.entry = None


class NumericsPolicy:
    """Configuration for the response ladder.

    - ``spike_zscore`` / ``ewma_alpha`` / ``warmup_steps``: a finite loss
      whose z-score against the running EWMA (updated with ``ewma_alpha``)
      exceeds ``spike_zscore`` — after ``warmup_steps`` healthy steps — is a
      spike.
    - ``max_rewinds``: total :class:`LossSpike` raises; past the budget a
      spike is *accepted* (folded into the EWMA) so a deterministic replay
      that re-hits the same spike cannot rewind forever.
    - ``bisect_after`` consecutive non-finite steps trigger bisection;
      ``bisect=False`` raises :class:`PersistentNonFinite` instead.
    - ``replay_dir``: where anomaly replay bundles are dumped (``None`` =
      no dumps); ``dump_inputs=False`` keeps the step inputs out of the
      bundle (they can be model-sized).
    """

    def __init__(self, *, spike_zscore: float = 6.0, ewma_alpha: float = 0.05,
                 warmup_steps: int = 10, max_rewinds: int = 2,
                 bisect_after: int = 3, bisect: bool = True,
                 replay_dir: str | None = None, dump_inputs: bool = True):
        self.spike_zscore = spike_zscore
        self.ewma_alpha = ewma_alpha
        self.warmup_steps = warmup_steps
        self.max_rewinds = max_rewinds
        self.bisect_after = bisect_after
        self.bisect = bisect
        self.replay_dir = replay_dir
        self.dump_inputs = dump_inputs


# process-installed policy: ElasticTrainer(numerics_policy=...) installs it
# here so guards jitted without an explicit policy pick up the trainer's
_installed_policy: NumericsPolicy | None = None


def install_policy(policy: NumericsPolicy | None) -> NumericsPolicy | None:
    """Install ``policy`` process-wide; returns the previous one (restore it
    when a supervision scope ends)."""
    global _installed_policy
    prev = _installed_policy
    _installed_policy = policy
    return prev


def installed_policy() -> NumericsPolicy | None:
    return _installed_policy


# health-word layout (f32 vector emitted by NumericsGuardTransform)
IDX_NONFINITE_GRADS = 0
IDX_NONFINITE_LOSS = 1
IDX_NONFINITE_STATE = 2
IDX_GRAD_NORM = 3
IDX_LOSS = 4
HEALTH_SIZE = 5


class Verdict:
    """One step's parsed health word."""

    __slots__ = ("step", "nonfinite_grads", "nonfinite_loss", "nonfinite_state",
                 "grad_norm", "loss", "healthy", "skipped", "probe")

    def __init__(self, word, *, step: int = 0, probe: bool = False):
        import numpy as np

        w = np.asarray(word, dtype=np.float64).reshape(-1)
        self.step = step
        self.nonfinite_grads = float(w[IDX_NONFINITE_GRADS])
        self.nonfinite_loss = float(w[IDX_NONFINITE_LOSS])
        self.nonfinite_state = float(w[IDX_NONFINITE_STATE])
        self.grad_norm = float(w[IDX_GRAD_NORM])
        self.loss = float(w[IDX_LOSS])
        total = self.nonfinite_grads + self.nonfinite_loss + self.nonfinite_state
        # a NaN count (the reductions themselves corrupted) is unhealthy too
        self.healthy = math.isfinite(total) and total == 0.0
        self.skipped = not self.healthy
        self.probe = probe

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"<Verdict step={self.step} healthy={self.healthy} "
                f"nonfinite=(g={self.nonfinite_grads:.0f} l={self.nonfinite_loss:.0f} "
                f"s={self.nonfinite_state:.0f}) grad_norm={self.grad_norm:.4g} "
                f"loss={self.loss:.6g}>")


# every live sentinel, weakly held: a supervisor restoring a checkpoint for
# a NON-spike failure (crash, preemption replay) must also suppress EWMA
# refolds on whatever guards its step function carries — it has no exception
# object pointing at them, so it broadcasts via notify_rewind_all
_live_sentinels: "weakref.WeakSet" = weakref.WeakSet()


def notify_rewind_all(replay_steps: int) -> None:
    """Broadcast :meth:`NumericsSentinel.notify_rewind` to every live
    sentinel. Called by ``ElasticTrainer`` (when ``numerics_policy`` is
    armed) on any restore-and-replay; with several independent trainers in
    one process, prefer per-exception delivery where available."""
    for s in list(_live_sentinels):
        s.notify_rewind(replay_steps)


class NumericsSentinel:
    """Per-guard host state machine: consumes health words, keeps the loss
    EWMA and skip counters, raises the ladder's anomalies."""

    def __init__(self, policy: NumericsPolicy | None = None):
        self._policy = policy
        self.steps = 0              # health words ingested (non-probe)
        self.healthy_steps = 0
        self.nonfinite_steps = 0
        self.skipped_steps = 0
        self.consecutive_nonfinite = 0
        self.rewind_raises = 0      # LossSpike raises (the trainer rewinds)
        self.spikes_accepted = 0    # spikes past the rewind budget
        self.ewma_mean: float | None = None
        self.ewma_var = 0.0
        self.last_verdict: Verdict | None = None
        self._probing = 0
        self._fold_suppress = 0  # healthy losses to re-judge but NOT re-fold
        # (set via notify_rewind: the rewind's replayed steps were already
        # folded once; folding them again would deflate the EWMA variance)
        _live_sentinels.add(self)
        self._replay_source = None  # (fn_name, entry, inps) set per call by
        # the guard wrapper so bundles can include the exact step inputs

    @property
    def policy(self) -> NumericsPolicy:
        if self._policy is not None:
            return self._policy
        return _installed_policy or _DEFAULT_POLICY

    # -- probe mode (bisection) ---------------------------------------------
    @contextmanager
    def probing(self):
        """Bisection probes parse health words (``last_verdict``) without
        counting, EWMA updates, or anomaly raises."""
        self._probing += 1
        try:
            yield
        finally:
            self._probing -= 1

    def reset_episode(self) -> None:
        """Called after a successful containment (e.g. the bisected kernel
        was quarantined) so the re-run doesn't immediately re-escalate."""
        self.consecutive_nonfinite = 0

    def notify_rewind(self, replay_steps: int) -> None:
        """The supervisor restored a checkpoint and is about to replay
        ``replay_steps`` steps this sentinel has already seen. Replayed
        healthy losses are re-*judged* against the frozen pre-spike
        statistics but not re-*folded* — re-folding near-identical values
        shrinks the variance each rewind, making ordinary post-rewind
        wiggles look like spikes. Every replayed ingest (healthy or
        in-graph-skipped) consumes one slot of the window, mirroring
        whether it folded in its first life."""
        self._fold_suppress += max(int(replay_steps), 0)

    # -- ingestion ----------------------------------------------------------
    def ingest(self, health_word, *, has_state_select: bool = True) -> Verdict:
        if self._probing:
            v = Verdict(health_word, step=self.steps, probe=True)
            self.last_verdict = v
            return v
        pol = self.policy
        self.steps += 1
        v = Verdict(health_word, step=self.steps)
        self.last_verdict = v
        if not v.healthy:
            if self._fold_suppress > 0:
                # a replayed SKIPPED step: it never folded in its first life
                # either, but it still occupies one slot of the replay window
                self._fold_suppress -= 1
            self.nonfinite_steps += 1
            self.consecutive_nonfinite += 1
            _observe.inc("runtime.nonfinite_steps")
            if has_state_select:
                self.skipped_steps += 1
                _observe.inc("runtime.skipped_steps")
            _observe.event("sentinel_skip", step=v.step,
                           nonfinite_grads=v.nonfinite_grads,
                           nonfinite_loss=v.nonfinite_loss,
                           nonfinite_state=v.nonfinite_state,
                           consecutive=self.consecutive_nonfinite)
            if self.consecutive_nonfinite == 1:
                self.maybe_dump("skip", v)
            if self.consecutive_nonfinite >= pol.bisect_after:
                self.maybe_dump("persistent_nonfinite", v)
                if pol.bisect:
                    raise SilentNumericsFault(v)
                raise PersistentNonFinite(
                    f"{self.consecutive_nonfinite} consecutive non-finite "
                    f"steps at the same trace point (bisection disabled)")
            return v
        # healthy step
        self.consecutive_nonfinite = 0
        self.healthy_steps += 1
        if math.isfinite(v.grad_norm):
            # an f32 sumsq can overflow to inf on finite-but-huge grads; a
            # non-finite sample would permanently corrupt the histogram sum
            _observe.observe_value("runtime.grad_norm", v.grad_norm)
        if math.isfinite(v.loss):
            self._check_spike_and_fold(v, pol)
        return v

    def _check_spike_and_fold(self, v: Verdict, pol: NumericsPolicy) -> None:
        if self.ewma_mean is None:
            self.ewma_mean = v.loss
            self.ewma_var = 0.0
            _observe.set_gauge("runtime.loss_ewma", self.ewma_mean)
            return
        std = math.sqrt(max(self.ewma_var, 0.0))
        # floor: relative to the mean so a flat early loss curve doesn't make
        # every wiggle an infinite-z spike
        floor = 1e-3 * abs(self.ewma_mean) + 1e-8
        z = (v.loss - self.ewma_mean) / max(std, floor)
        if self.healthy_steps > pol.warmup_steps and z > pol.spike_zscore:
            if self.rewind_raises < pol.max_rewinds:
                self.rewind_raises += 1
                _observe.event("sentinel_spike", step=v.step, loss=v.loss,
                               ewma=self.ewma_mean, z=z)
                self.maybe_dump("spike", v)
                # NOT folded into the EWMA: the replay re-judges this loss
                # against the pre-spike statistics. The exception carries the
                # sentinel so the supervisor can notify_rewind() with the
                # replay length once the restore actually happens.
                err = LossSpike(step=v.step, loss=v.loss, ewma=self.ewma_mean, z=z)
                err.sentinel = self
                raise err
            self.spikes_accepted += 1
            _observe.event("sentinel_spike_accepted", step=v.step, loss=v.loss,
                           z=z, rewinds_spent=self.rewind_raises)
        if self._fold_suppress > 0:
            # a replayed step after a rewind: judged above, already folded
            # in its first life — skip the refold
            self._fold_suppress -= 1
            return
        d = v.loss - self.ewma_mean
        a = pol.ewma_alpha
        self.ewma_mean += a * d
        self.ewma_var = (1.0 - a) * (self.ewma_var + a * d * d)
        _observe.set_gauge("runtime.loss_ewma", self.ewma_mean)

    # -- replay bundles ------------------------------------------------------
    def maybe_dump(self, kind: str, verdict: Verdict) -> str | None:
        pol = self.policy
        if pol.replay_dir is None:
            return None
        try:
            fn_name, entry, inps, decisions = \
                self._replay_source or ("fn", None, None, None)
            return dump_replay_bundle(
                pol.replay_dir, kind=kind, verdict=verdict, fn_name=fn_name,
                entry=entry, inputs=inps if pol.dump_inputs else None,
                decisions=decisions)
        except Exception:
            return None  # diagnostics must never take the step down

    # -- reporting -----------------------------------------------------------
    def summary(self) -> str:
        lines = [f"steps={self.steps} healthy={self.healthy_steps} "
                 f"nonfinite={self.nonfinite_steps} skipped={self.skipped_steps}",
                 f"rewind_raises={self.rewind_raises} "
                 f"spikes_accepted={self.spikes_accepted}"]
        if self.ewma_mean is not None:
            lines.append(f"loss ewma={self.ewma_mean:.6g} "
                         f"std={math.sqrt(max(self.ewma_var, 0.0)):.4g}")
        if self.last_verdict is not None:
            lines.append(f"last: {self.last_verdict!r}")
        return "\n".join(lines)


_DEFAULT_POLICY = NumericsPolicy()


# ---------------------------------------------------------------------------
# bisection: attribute persistent non-finite output to one claimed kernel
# ---------------------------------------------------------------------------

def claimed_kernel_ids(exec_trc) -> list[str]:
    """Claim ids of the custom (operator-executor) kernels in an execution
    trace — the bisection candidate set. Fusion regions (XLA) are the
    fallback, not candidates — but claimed kernels *absorbed into* an XLA
    region (``xla_absorb_claimed``) live in its subsymbols, so the walk
    recurses."""
    from thunder_tpu.executors import FusionExecutor

    ids: set[str] = set()

    def walk(bsyms):
        for b in bsyms:
            ex = b.sym.executor
            if ex is None:
                continue
            if isinstance(ex, FusionExecutor):
                walk(b.subsymbols)
            else:
                ids.add(str(b.sym.id))

    walk(exec_trc.bound_symbols)
    return sorted(ids)


def inputs_alive(tree) -> bool:
    """False when any jax array leaf of ``tree`` has been donated/deleted —
    such inputs cannot be re-run by bisection probes (the failing call's
    ``donate_argnums`` consumed their buffers)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                if leaf.is_deleted():
                    return False
            except Exception:
                continue
    return True


def _memoized_probe(probe):
    last = {"set": None, "healthy": None}

    def _probe(disabled):
        key = frozenset(disabled)
        if key == last["set"]:
            return last["healthy"]  # a probe is a full recompile+run — never
            # repeat the identical configuration (e.g. the final confirm
            # after the search already ended on that exact set)
        _observe.inc("runtime.bisection_probes")
        last["set"], last["healthy"] = key, bool(probe(key))
        return last["healthy"]

    return _probe


def attribute_offenders(candidates, probe) -> list[str]:
    """Attribute persistent non-finite output to claimed kernels.

    ``probe(disabled: frozenset[str]) -> bool`` must recompile the step with
    those claim ids disabled, re-run it on the failing inputs, and report
    whether the health word came back healthy. Fast path: binary search for
    the single offender (log2 probes — the overwhelmingly common case).
    When the search fails but disabling EVERY candidate was healthy, the
    fault is provably kernel-borne with multiple simultaneous offenders —
    fall back to a linear leave-one-enabled sweep (each candidate enabled
    alone against the rest disabled; unhealthy means it corrupts by
    itself). Returns ``[]`` when disabling everything still yields
    non-finite output (the corruption is upstream of the custom kernels)."""
    cands = sorted(candidates)
    if not cands:
        return []
    _probe = _memoized_probe(probe)
    if not _probe(cands):
        return []  # all custom kernels off, still corrupt: not kernel-borne
    search = list(cands)
    while len(search) > 1:
        half = search[:len(search) // 2]
        if _probe(half):
            search = half  # disabling this group removed the corruption
        else:
            search = search[len(search) // 2:]
    if _probe(search):
        return [search[0]]
    # multiple simultaneous offenders: x is one iff the step stays corrupt
    # with ONLY x enabled (every other candidate disabled)
    offenders = [x for x in cands if not _probe(set(cands) - {x})]
    if offenders and _probe(offenders):
        return offenders
    return []


def bisect_offender(candidates, probe) -> str | None:
    """Single-offender form of :func:`attribute_offenders` (``None`` for
    upstream corruption or multi-offender attribution)."""
    offs = attribute_offenders(candidates, probe)
    return offs[0] if len(offs) == 1 else None


# ---------------------------------------------------------------------------
# replay bundles
# ---------------------------------------------------------------------------

def dump_replay_bundle(directory: str, *, kind: str, verdict: Verdict,
                       fn_name: str = "fn", entry=None, inputs=None,
                       decisions=None) -> str:
    """Write an offline-repro bundle for an anomaly: ``meta.json`` (verdict,
    trace hash, decision log, RNG state, time) plus ``inputs.npz`` (the
    exact step inputs, when provided). Returns the bundle directory."""
    import numpy as np

    bundle = os.path.join(
        os.path.abspath(directory),
        f"{fn_name}-step{verdict.step}-{kind}-{int(time.time() * 1e3)}")
    os.makedirs(bundle, exist_ok=True)
    meta: dict = {"kind": kind, "fn": fn_name, "time": time.time(),
                  "verdict": verdict.to_dict()}
    if entry is not None and getattr(entry, "traces", None):
        src = str(entry.traces[-1])
        meta["trace_hash"] = hashlib.sha1(src.encode()).hexdigest()
        with open(os.path.join(bundle, "execution_trace.py"), "w") as f:
            f.write(src)
    try:
        import thunder_tpu as tt

        key = tt._rng_state.get("key")
        if key is not None:
            meta["rng_key"] = [int(x) for x in np.asarray(key).reshape(-1)]
    except Exception:
        pass
    if decisions is not None:
        meta["decisions"] = decisions
    with open(os.path.join(bundle, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    if inputs is not None:
        arrays = {}
        for i, x in enumerate(inputs):
            try:
                arrays[f"arg{i}"] = np.asarray(x)
            except Exception:
                continue
        if arrays:
            np.savez(os.path.join(bundle, "inputs.npz"), **arrays)
    _observe.event("replay_bundle", kind=kind, path=bundle)
    return bundle

"""Layered fault injection: named domains, deterministic schedules.

The old ``elastic.FaultInjector`` could only raise *between* training steps.
Recovery paths below the step loop — a Pallas kernel that dies at compile,
a collective that times out, a checkpoint write that tears — were untestable.
This module generalizes it: a :class:`FaultPlan` holds :class:`FaultSpec`
entries addressed to *injection domains*, and the runtime calls
:func:`maybe_fail` at each layer's hook point:

==================  =========================================================
domain              hook point
==================  =========================================================
``compile``         ``ThunderTPUFunction._compile_inner`` (trace→executable)
``dispatch``        the ``CacheEntry.run_fn`` wrapper (one check per step)
``kernel:<claim>``  every ``register_operator`` claim impl — e.g.
                    ``kernel:pallas.rms_norm`` fires inside the guarded
                    Pallas kernel (at trace time under the whole-program
                    jit = a compile-phase kernel fault; per call on the
                    eager per-region path = a runtime kernel fault)
``collective``      the eager lowerings in ``distributed/prims.py``
``checkpoint_io``   ``checkpoint.save_checkpoint``
``step``            ``ElasticTrainer``'s step loop AND the serving
                    engine's batched decode dispatch (legacy serving
                    domain, kept for existing chaos plans)
``serving:prefill``  the serving engine's prefill-chunk dispatch
                    (pre-dispatch, so a retried transient replays on
                    unconsumed inputs)
``serving:decode``   the serving engine's batched decode dispatch
                    (pre-dispatch; retried like ``step``)
``serving:admission``  the scheduler's admission path, BEFORE pages are
                    allocated — contained locally (the request stays
                    queued and retries next engine step)
``serving:engine``  the serving engine's fatal-crash domain: fires in
                    the decode dispatch and CONSUMES the donated page
                    pools first (what a real mid-execution accelerator
                    fault does), so the retry classifier escalates FATAL
                    and ``serving.supervisor.EngineSupervisor`` restarts
                    the engine (pool rebuild + re-prefill)
``numerics:*``      silent-data faults — these *corrupt values* instead of
                    raising. ``numerics:grads`` / ``numerics:loss`` poison
                    the gradients / loss of a ``NumericsGuardTransform``-ed
                    step (the guard feeds a NaN poison scalar into the
                    compiled program, so the corruption flows through the
                    real graph); ``numerics:kernel:<claim>`` NaN-poisons the
                    output of that claimed kernel inside ``kernel_guard``
                    (at trace time under the whole-program jit, so use
                    ``transient=False`` — the corruption is baked into every
                    compile while the spec stays live)
==================  =========================================================

Schedules are deterministic so chaos tests are reproducible: explicit step
sets (``at_steps``), every-N invocation counting (``every_n``), or seeded
probability (``probability`` + ``seed``). ``transient=True`` (default) makes
a fault fire once per schedule point and then clear — the retry/replay path
sees a healthy system; ``transient=False`` is a permanent fault that fires
on every matching invocation (bounded by ``max_fires``).

When no plan is installed every hook costs one module-global ``is None``
check — the production path pays nothing.
"""

from __future__ import annotations

import functools
import random
import threading
from contextlib import contextmanager
from typing import Callable

from thunder_tpu.observe import registry as _observe


class InjectedFault(RuntimeError):
    """Raised by :func:`maybe_fail` when a :class:`FaultSpec` fires."""

    def __init__(self, message: str, *, domain: str = "", step: int | None = None,
                 transient: bool = True):
        super().__init__(message)
        self.domain = domain
        self.step = step
        self.transient = transient


class KernelExecutionError(RuntimeError):
    """A claimed custom kernel failed. Carries the claim id so the dispatch
    layer can quarantine exactly that kernel and recompile with the claim
    disabled (XLA fallback) instead of taking the job down.

    ``phase`` is ``"compile"`` when the failure surfaced while the impl was
    being traced (jit/lowering time) and ``"runtime"`` when it ran eagerly.
    """

    def __init__(self, claim_id: str, phase: str = "runtime",
                 cause: BaseException | None = None):
        super().__init__(f"claimed kernel {claim_id!r} failed at {phase} time: "
                         f"{cause!r}")
        self.claim_id = claim_id
        self.phase = phase


class FaultSpec:
    """One injected fault: a domain plus a deterministic schedule.

    Exactly-one-of ``at_steps`` / ``every_n`` / ``probability`` selects the
    schedule; with none given the spec fires on every matching invocation
    (once total when ``transient``).
    """

    __slots__ = ("domain", "at_steps", "every_n", "probability", "seed",
                 "transient", "max_fires", "exc", "_rng", "_calls", "_fires",
                 "_fired_steps")

    def __init__(self, domain: str, *, at_steps=None, every_n: int | None = None,
                 probability: float | None = None, seed: int = 0,
                 transient: bool = True, max_fires: int | None = None,
                 exc: Callable[[str], BaseException] | None = None):
        self.domain = domain
        self.at_steps = set(at_steps) if at_steps is not None else None
        self.every_n = every_n
        self.probability = probability
        self.seed = seed
        self.transient = transient
        self.max_fires = max_fires
        self.exc = exc
        self._rng = random.Random(seed)
        self._calls = 0
        self._fires = 0
        self._fired_steps: set[int] = set()

    def matches(self, domain: str) -> bool:
        if self.domain.endswith("*"):
            return domain.startswith(self.domain[:-1])
        return domain == self.domain

    def should_fire(self, step: int | None) -> bool:
        """Advance this spec's deterministic schedule by one invocation and
        report whether the fault fires. Not thread-safe on its own — the
        owning :class:`FaultPlan` serializes calls."""
        self._calls += 1
        if self.max_fires is not None and self._fires >= self.max_fires:
            return False
        if self.at_steps is not None:
            if step is None or step not in self.at_steps:
                return False
            if self.transient and step in self._fired_steps:
                return False
            self._fired_steps.add(step)
        elif self.every_n is not None:
            if self._calls % self.every_n != 0:
                return False
        elif self.probability is not None:
            if self._rng.random() >= self.probability:
                return False
        elif self.transient and self._fires > 0:
            # unscheduled transient fault: fires exactly once, ever
            return False
        self._fires += 1
        return True


class FaultPlan:
    """A set of :class:`FaultSpec` entries consulted by every hook point."""

    def __init__(self, specs=()):
        self.specs = list(specs)
        self._lock = threading.Lock()

    def add(self, spec: FaultSpec) -> "FaultPlan":
        self.specs.append(spec)
        return self

    def maybe_fail(self, domain: str, *, step: int | None = None,
                   site: str | None = None) -> None:
        for spec in self.specs:
            if not spec.matches(domain):
                continue
            with self._lock:
                fire = spec.should_fire(step)
            if not fire:
                continue
            _observe.inc("runtime.faults_injected")
            _observe.event("fault_injected", domain=domain, step=step, site=site,
                           transient=spec.transient)
            where = f" at step {step}" if step is not None else ""
            at = f" ({site})" if site else ""
            if spec.exc is not None:
                raise spec.exc(f"injected fault in domain {domain!r}{where}{at}")
            raise InjectedFault(
                f"injected {'transient' if spec.transient else 'permanent'} "
                f"fault in domain {domain!r}{where}{at}",
                domain=domain, step=step, transient=spec.transient)

    def affects_compile(self) -> bool:
        """True when any spec could fire inside a traced kernel impl
        (``numerics:kernel:*``): such corruption is baked into the compiled
        executable, so the dispatch cache key must include the plan's
        identity — an entry compiled under the plan must never serve after
        it is cleared. (Crash-domain kernel faults raise at compile time
        and never produce a cached entry, so they don't need this.)"""
        target = "numerics:kernel"
        for spec in self.specs:
            if spec.domain.endswith("*"):
                prefix = spec.domain[:-1]
                if prefix.startswith(target) or target.startswith(prefix):
                    return True
            elif spec.domain.startswith(target):
                return True
        return False

    def should_corrupt(self, domain: str, *, step: int | None = None,
                       site: str | None = None) -> bool:
        """Silent-data variant of :meth:`maybe_fail` for the ``numerics:*``
        domains: advances the matching specs' schedules and reports whether
        a corruption fires (the caller poisons values instead of raising)."""
        for spec in self.specs:
            if not spec.matches(domain):
                continue
            with self._lock:
                fire = spec.should_fire(step)
            if fire:
                _observe.inc("runtime.faults_injected")
                _observe.event("numeric_fault_injected", domain=domain, step=step,
                               site=site, transient=spec.transient)
                return True
        return False


# ---------------------------------------------------------------------------
# the process-wide active plan (None = zero-cost hooks)
# ---------------------------------------------------------------------------

_active_plan: FaultPlan | None = None


def install(plan: FaultPlan | None) -> None:
    """Install ``plan`` process-wide (``None`` clears it)."""
    global _active_plan
    _active_plan = plan


def clear() -> None:
    install(None)


def active_plan() -> FaultPlan | None:
    return _active_plan


@contextmanager
def active(plan: FaultPlan):
    """Scope a plan to a ``with`` block (restores the previous plan)."""
    global _active_plan
    prev = _active_plan
    _active_plan = plan
    try:
        yield plan
    finally:
        _active_plan = prev


def maybe_fail(domain: str, *, step: int | None = None,
               site: str | None = None) -> None:
    """The hook every instrumented layer calls. One ``is None`` check when
    no plan is installed."""
    if _active_plan is None:
        return
    _active_plan.maybe_fail(domain, step=step, site=site)


def should_corrupt(domain: str, *, step: int | None = None,
                   site: str | None = None) -> bool:
    """Hook for the silent-data (``numerics:*``) domains: True when a value
    corruption should be injected now. One ``is None`` check when no plan is
    installed."""
    if _active_plan is None:
        return False
    return _active_plan.should_corrupt(domain, step=step, site=site)


def poison_tree(tree):
    """NaN-poison every inexact array leaf of ``tree`` (jax values or
    tracers — works at trace time inside a jit as well as eagerly). Integer
    and non-array leaves pass through untouched."""
    import jax
    import jax.numpy as jnp

    def _p(x):
        try:
            dt = jnp.result_type(x)
        except Exception:
            return x
        if jnp.issubdtype(dt, jnp.inexact):
            return x + jnp.asarray(float("nan"), dt)
        return x

    return jax.tree_util.tree_map(_p, tree)


# ---------------------------------------------------------------------------
# kernel guard: fault hook + failure attribution for claimed kernels
# ---------------------------------------------------------------------------

def _looks_traced(args, kwargs) -> bool:
    """True when any argument is a jax tracer — the guarded impl is being
    traced into a jit program, so a failure here is a compile-phase failure.
    Checked by mro name to avoid pinning a jax.core import surface."""
    for x in list(args) + list(kwargs.values()):
        if any(c.__name__ == "Tracer" for c in type(x).__mro__):
            return True
    return False


def kernel_guard(claim_id: str, fn: Callable) -> Callable:
    """Wrap a claimed kernel impl (``OperatorExecutor.register_operator``):

    1. fault hook for the ``kernel:<claim_id>`` injection domain, and
    2. failure attribution — any exception escaping the impl is re-raised as
       :class:`KernelExecutionError` carrying ``claim_id`` and the phase, so
       the dispatch layer can quarantine the kernel and fall back to XLA.
    """
    domain = f"kernel:{claim_id}"

    numerics_domain = f"numerics:{domain}"

    @functools.wraps(fn)
    def guarded(*args, **kwargs):
        try:
            maybe_fail(domain, site=claim_id)
            out = fn(*args, **kwargs)
            # silent-data fault: the kernel "succeeds" but returns garbage —
            # the failure mode the numerics sentinel exists to catch. Under
            # the whole-program jit this runs at trace time, baking the
            # corruption into the compiled program (use transient=False so
            # every recompile, including bisection probes, stays corrupt).
            if _active_plan is not None and should_corrupt(numerics_domain,
                                                           site=claim_id):
                out = poison_tree(out)
            return out
        except KernelExecutionError:
            raise  # a nested claim already attributed itself
        except Exception as e:
            # phase detection only on the failure path: the healthy per-call
            # cost stays the module's one is-None check in maybe_fail
            phase = "compile" if _looks_traced(args, kwargs) else "runtime"
            raise KernelExecutionError(claim_id, phase=phase, cause=e) from e

    guarded.__wrapped__ = fn
    return guarded

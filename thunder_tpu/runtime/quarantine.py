"""Kernel quarantine: remember which claimed kernels are broken, compile
around them.

When a claimed custom kernel (a Pallas claim) fails at compile or at
runtime, the dispatch layer calls :func:`get_quarantine().add(claim_id)` and
recompiles; the claim pass (``executors/passes.py``) consults
:func:`quarantine_reason` before offering a bound symbol to an executor, so
the quarantined claim is rejected with a ``"quarantined: ..."`` decision
record (visible in ``observe.explain()``) and the op falls through to the
XLA executor's lowering — graceful degradation instead of a dead job.

Persistence: :func:`configure` points the quarantine at a directory (by
default the persistent compile cache directory, wired through
``thunder_tpu.enable_compilation_cache``); the set is written as JSON next
to the cached executables, so a restarted process skips the known-bad
kernel *before* paying a doomed compile. ``THUNDER_TPU_QUARANTINE_DIR``
configures it from the environment.

Every mutation bumps a process-wide *epoch* that joins the dispatch cache
key, so entries compiled before a quarantine event can never serve after it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar

from thunder_tpu.observe import registry as _observe

_FILENAME = "kernel_quarantine.json"

_epoch = 0
_epoch_lock = threading.Lock()


def _bump_epoch() -> None:
    global _epoch
    with _epoch_lock:
        _epoch += 1


def epoch() -> int:
    """Monotonic counter of quarantine mutations; part of the dispatch
    cache key (a stale entry embedding a quarantined kernel never hits)."""
    return _epoch


class KernelQuarantine:
    """The quarantine set: claim id -> {reason, phase, time, count}."""

    def __init__(self, path: str | None = None):
        self._lock = threading.Lock()
        self._kernels: dict[str, dict] = {}
        self._path: str | None = None
        if path is not None:
            self.attach(path)

    # -- persistence --------------------------------------------------------
    def attach(self, path: str) -> None:
        """Bind to ``path`` (a JSON file): merge whatever a previous process
        quarantined there, then persist the union."""
        path = os.path.abspath(path)
        with self._lock:
            self._path = path
            disk = self._load(path)
            for k, rec in disk.items():
                self._kernels.setdefault(k, rec)
            self._persist()
        _bump_epoch()
        _observe.set_gauge("runtime.quarantined_kernels", len(self._kernels))

    @staticmethod
    def _load(path: str) -> dict:
        try:
            with open(path) as f:
                data = json.load(f)
            kernels = data.get("kernels", {})
            return kernels if isinstance(kernels, dict) else {}
        except Exception:
            return {}  # missing or torn file: start empty, rewrite on add

    def _persist(self) -> None:
        if self._path is None:
            return
        tmp = self._path + ".tmp"
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"version": 1, "kernels": self._kernels}, f, indent=2)
        os.replace(tmp, self._path)

    # -- mutation -----------------------------------------------------------
    def add(self, claim_id: str, *, reason: str = "", phase: str = "runtime") -> None:
        with self._lock:
            rec = self._kernels.get(claim_id)
            if rec is None:
                self._kernels[claim_id] = {"reason": reason, "phase": phase,
                                           "time": time.time(), "count": 1}
            else:
                rec["count"] = rec.get("count", 0) + 1
                rec["reason"] = reason or rec.get("reason", "")
            self._persist()
            n = len(self._kernels)
        _bump_epoch()
        _observe.set_gauge("runtime.quarantined_kernels", n)
        _observe.event("kernel_quarantined", claim=claim_id, reason=reason,
                       phase=phase)

    def remove(self, claim_id: str) -> None:
        with self._lock:
            self._kernels.pop(claim_id, None)
            self._persist()
            n = len(self._kernels)
        _bump_epoch()
        _observe.set_gauge("runtime.quarantined_kernels", n)

    def clear(self) -> None:
        with self._lock:
            self._kernels.clear()
            self._persist()
        _bump_epoch()
        _observe.set_gauge("runtime.quarantined_kernels", 0)

    # -- queries ------------------------------------------------------------
    def reason(self, claim_id: str) -> str | None:
        rec = self._kernels.get(claim_id)
        if rec is None:
            return None
        return rec.get("reason") or f"quarantined at {rec.get('phase', '?')} time"

    def ids(self) -> tuple[str, ...]:
        return tuple(self._kernels)

    def __contains__(self, claim_id: str) -> bool:
        return claim_id in self._kernels

    def __len__(self) -> int:
        return len(self._kernels)

    @property
    def path(self) -> str | None:
        return self._path


# ---------------------------------------------------------------------------
# the process-wide quarantine
# ---------------------------------------------------------------------------

_active = KernelQuarantine()


def get_quarantine() -> KernelQuarantine:
    return _active


def configure(directory: str) -> KernelQuarantine:
    """Persist the quarantine set under ``directory`` (next to the compile
    cache): loads claim ids a previous process recorded there."""
    _active.attach(os.path.join(str(directory), _FILENAME))
    return _active


def reset(path: str | None = None) -> KernelQuarantine:
    """Replace the process quarantine with a fresh instance (test harness:
    simulates a process restart; pass ``path`` to re-read a persisted set)."""
    global _active
    _active = KernelQuarantine(path)
    _bump_epoch()
    _observe.set_gauge("runtime.quarantined_kernels", len(_active))
    return _active


def is_quarantined(claim_id: str) -> bool:
    return claim_id in _active


# temporary (non-persisted) claim disables: the numerics bisection recompiles
# with candidate kernel groups disabled to attribute a silent fault — these
# suppressions gate the claim pass exactly like a quarantine entry but never
# touch the persisted set. A ContextVar (not a module global): suppression
# is visible only to the bisection's own call chain — a concurrent compile
# on another thread never sees an unrelated probe's disables, and two
# concurrent bisections cannot clobber each other's suppression sets. The
# stored dict is treated as immutable (each suppress() installs a fresh
# copy). Cache correctness comes from :func:`suppression_key` joining the
# dispatch cache key — NOT from bumping the global epoch, which would
# permanently invalidate every other jitted function's cached entries on
# each probe enter/exit.
# the ContextVar holds (reasons_dict, precomputed_frozenset) so the hot
# dispatch path reads the cache-key component without allocating
_EMPTY_SUPPRESSION: tuple = ({}, frozenset())
_suppressed: ContextVar[tuple] = ContextVar("quarantine_suppressed",
                                            default=_EMPTY_SUPPRESSION)


def suppression_key() -> frozenset:
    """The context's active suppression set — part of the dispatch cache key
    (an entry compiled under one probe configuration only serves calls made
    under that same configuration). Precomputed at suppress() time: this is
    on the per-call dispatch path."""
    return _suppressed.get()[1]


@contextmanager
def suppress(claim_ids, reason: str = "bisection probe"):
    """Temporarily treat ``claim_ids`` as quarantined (scoped to this context,
    never persisted). Nests: inner suppressions stack on top of outer ones."""
    merged = dict(_suppressed.get()[0])
    for c in claim_ids:
        merged[c] = reason
    tok = _suppressed.set((merged, frozenset(merged)))
    try:
        yield
    finally:
        _suppressed.reset(tok)


def quarantine_reason(claim_id: str) -> str | None:
    r = _suppressed.get()[0].get(claim_id)
    if r is not None:
        return r
    return _active.reason(claim_id)


if os.environ.get("THUNDER_TPU_QUARANTINE_DIR"):
    configure(os.environ["THUNDER_TPU_QUARANTINE_DIR"])

"""Developer transforms: per-op debug callbacks and profiler annotation.

Reference parity: ``thunder/dev_utils/`` — ``DebugTransform``
(``debug_transform.py:15``, inject callbacks per bound symbol) and
``NvtxProfileTransform`` (``nvtx_profile_transform.py:42``, wrap every bsym
in nvtx push/pop). TPU equivalents: python-level callbacks interleaved into
the generated program, and ``jax.profiler`` trace annotations around
executor callables (visible in TensorBoard / Perfetto next to the XLA
timeline — the NVTX analog).
"""

from __future__ import annotations

from typing import Any, Callable

from thunder_tpu.core.prims import PrimIDs
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, from_trace
from thunder_tpu.core.transform_common import Transform

_SKIP = (PrimIDs.PYTHON_RETURN, PrimIDs.COMMENT, PrimIDs.PYTHON_DEL)


class DebugTransform(Transform):
    """Interleave ``callback(name, bsym, outputs)`` after every executed
    operation of the final program. The callback receives concrete arrays —
    use for nan-hunting, per-op logging, or golden-value capture."""

    def __init__(self, callback: Callable[[str, BoundSymbol, Any], None]):
        self.callback = callback

    def transform_trace_post_optimization(self, trc: TraceCtx, **kwargs) -> TraceCtx:
        new = from_trace(trc)
        bsyms: list[BoundSymbol] = []
        cb = self.callback
        for i, bsym in enumerate(trc.bound_symbols):
            bsyms.append(bsym)
            if bsym.sym.id in _SKIP:
                continue
            outs = bsym.flat_proxy_outs()
            if not outs:
                continue
            name = bsym.sym.codegen_name()

            def make_impl(_name, _bsym):
                def debug_cb(*vals):
                    cb(_name, _bsym, vals)
                    return None

                return debug_cb

            dbg = Symbol(f"debug_{i}", None, id=f"debug:{i}", is_prim=True,
                         python_impl=make_impl(name, bsym))
            bsyms.append(dbg.bind(*outs, output=None))
        new.bound_symbols = bsyms
        new.set_provenance("Debug transform")
        return new


class ProfileTransform(Transform):
    """Wrap every executor callable in a ``jax.profiler.TraceAnnotation`` so
    per-region spans appear in profiler traces alongside XLA ops. When the
    ``thunder_tpu.observe`` registry is enabled, each wrapped call also
    records an observe span (cat ``op``) visible in
    ``observe.export_chrome_trace``.

    Region names come from ``observe.profile.region_names_for`` — the ONE
    owner of the naming scheme (``executor:symbol#occurrence``) shared with
    the dispatch-time ``jax.named_scope`` annotations, the measured-time
    :class:`~thunder_tpu.observe.profile.StepProfile` and the residual
    ledger — so this transform's profiler output joins against the decision
    log by name, not by guesswork. ``prefix`` namespaces the annotation
    (``<prefix>/<region>``) without changing the region id itself.

    NOTE: under the default whole-program jit the wrapped impls execute
    once, at jax trace time — you get one trace-time span per op, not a
    per-step runtime timeline; compile with ``whole_program_jit=False``
    (the per-region execution path) for real per-op runtime spans."""

    def __init__(self, prefix: str = "thunder_tpu"):
        self.prefix = prefix

    def transform_trace_post_optimization(self, trc: TraceCtx, **kwargs) -> TraceCtx:
        import jax

        from thunder_tpu.observe import registry as _observe
        from thunder_tpu.observe.profile import region_names_for

        names = region_names_for(trc)
        new = from_trace(trc)
        bsyms: list[BoundSymbol] = []
        for bsym, region in zip(trc.bound_symbols, names):
            if region is None or bsym.sym.python_impl is None:
                bsyms.append(bsym)
                continue
            name = f"{self.prefix}/{region}" if self.prefix else region
            inner = bsym.sym.python_impl

            def make_impl(_name, _inner):
                def profiled(*args, **kw):
                    with jax.profiler.TraceAnnotation(_name), \
                            _observe.span(_name, cat="op"):
                        return _inner(*args, **kw)

                return profiled

            sym = Symbol(bsym.sym.name, bsym.sym.meta, id=bsym.sym.id,
                         is_prim=bsym.sym.is_prim, executor=bsym.sym.executor,
                         python_impl=make_impl(name, inner), tags=bsym.sym.tags)
            bsyms.append(bsym.from_bsym(sym=sym))
        new.bound_symbols = bsyms
        new.set_provenance("Profile transform")
        return new


def profile_run(fn: Callable, logdir: str, *args, **kwargs):
    """Run ``fn`` under a jax profiler trace written to ``logdir`` (view in
    TensorBoard or Perfetto)."""
    import jax

    with jax.profiler.trace(logdir):
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    return out

"""Configurable multi-family transformer — the litgpt model-zoo analog.

Reference parity: the reference's model zoo is the ``litgpt`` GPT consumed
through ``thunder/tests/litgpt_model.py`` (one configurable architecture
spanning GPT-2/Pythia/Falcon/Gemma/Phi/Llama via config flags). Same design
here, functional: one ``forward`` parameterized by

- ``norm``: "layernorm" | "rmsnorm"
- ``mlp``: "gelu" (GPT-2/Pythia/Phi), "swiglu" (Llama), "geglu" (Gemma)
- ``pos``: "rope" | "learned"; ``rotary_pct`` for partial rotary (NeoX/Phi)
- ``parallel_residual`` (NeoX/Falcon): attn and MLP read the same norm
- ``n_kv_heads``: MQA (Falcon) / GQA (Llama-3, Gemma)
- ``tie_embedding``: lm_head shares the token embedding (GPT-2, Gemma)
- ``emb_scale``: sqrt(dim) embedding scaling (Gemma)

Named configs carry the published geometries; tiny variants drive tests.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

from thunder_tpu import ops
from thunder_tpu.core import dtypes


@dataclass(frozen=True)
class Config:
    name: str = "tiny"
    vocab_size: int = 512
    dim: int = 64
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int | None = None
    intermediate_size: int | None = None  # default 4*dim (gelu) / computed (glu)
    max_seq_len: int = 256
    norm: str = "layernorm"          # "layernorm" | "rmsnorm"
    mlp: str = "gelu"                # "gelu" | "swiglu" | "geglu"
    pos: str = "rope"                # "rope" | "learned"
    rotary_pct: float = 1.0
    parallel_residual: bool = False
    tie_embedding: bool = False
    emb_scale: bool = False          # gemma: h *= sqrt(dim)
    qkv_bias: bool = False
    mlp_bias: bool = False
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: dtypes.dtype = dtypes.float32

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        return 4 * self.dim


CONFIGS = {
    # tests
    "tiny": Config(),
    "tiny-neox": Config(name="tiny-neox", parallel_residual=True, rotary_pct=0.25),
    "tiny-falcon": Config(name="tiny-falcon", parallel_residual=True, n_kv_heads=1),
    "tiny-gemma": Config(name="tiny-gemma", norm="rmsnorm", mlp="geglu", tie_embedding=True,
                         emb_scale=True, intermediate_size=128),
    "tiny-phi": Config(name="tiny-phi", rotary_pct=0.5, qkv_bias=True, mlp_bias=True),
    # published geometries (reference litgpt configs, litgpt_model.py:7-118)
    "pythia-410m": Config(name="pythia-410m", vocab_size=50304, dim=1024, n_layers=24,
                          n_heads=16, parallel_residual=True, rotary_pct=0.25,
                          max_seq_len=2048, dtype=dtypes.bfloat16),
    "falcon-7b": Config(name="falcon-7b", vocab_size=65024, dim=4544, n_layers=32,
                        n_heads=71, n_kv_heads=1, parallel_residual=True,
                        max_seq_len=2048, dtype=dtypes.bfloat16),
    "gemma-2b": Config(name="gemma-2b", vocab_size=256000, dim=2048, n_layers=18,
                       n_heads=8, n_kv_heads=1, norm="rmsnorm", mlp="geglu",
                       intermediate_size=16384, tie_embedding=True, emb_scale=True,
                       max_seq_len=8192, dtype=dtypes.bfloat16),
    "phi-1.5": Config(name="phi-1.5", vocab_size=50304, dim=2048, n_layers=24,
                      n_heads=32, rotary_pct=0.5, qkv_bias=True, mlp_bias=True,
                      max_seq_len=2048, dtype=dtypes.bfloat16),
    "gpt2-medium": Config(name="gpt2-medium", vocab_size=50257, dim=1024, n_layers=24,
                          n_heads=16, pos="learned", tie_embedding=True,
                          max_seq_len=1024, dtype=dtypes.bfloat16),
}


def init_params(cfg: Config, seed: int = 0, scale_layers: int | None = None):
    import jax
    import jax.numpy as jnp

    n_layers = scale_layers if scale_layers is not None else cfg.n_layers
    jd = cfg.dtype.jax
    D, F = cfg.dim, cfg.ffn_dim
    kv_dim = cfg.kv_heads * cfg.head_dim

    key = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(key, 8 + n_layers * 8))

    def dense(shape, fan_in):
        return (jax.random.normal(next(keys), shape, jnp.float32)
                / math.sqrt(fan_in)).astype(jd)

    def norm_params():
        p = {"w": jnp.ones((D,), jd)}
        if cfg.norm == "layernorm":
            p["b"] = jnp.zeros((D,), jd)
        return p

    params = {"wte": dense((cfg.vocab_size, D), D), "norm_f": norm_params(), "layers": []}
    if cfg.pos == "learned":
        params["wpe"] = dense((cfg.max_seq_len, D), D)
    if not cfg.tie_embedding:
        params["lm_head"] = dense((cfg.vocab_size, D), D)
    for _ in range(n_layers):
        layer = {
            "norm1": norm_params(),
            "wq": dense((D, D), D), "wk": dense((kv_dim, D), D), "wv": dense((kv_dim, D), D),
            "wo": dense((D, D), D),
        }
        if cfg.qkv_bias:
            layer["bq"] = jnp.zeros((D,), jd)
            layer["bk"] = jnp.zeros((kv_dim,), jd)
            layer["bv"] = jnp.zeros((kv_dim,), jd)
        if not cfg.parallel_residual:
            layer["norm2"] = norm_params()
        if cfg.mlp == "gelu":
            layer["w_fc"] = dense((F, D), D)
            layer["w_proj"] = dense((D, F), F)
            if cfg.mlp_bias:
                layer["b_fc"] = jnp.zeros((F,), jd)
                layer["b_proj"] = jnp.zeros((D,), jd)
        else:  # swiglu / geglu
            layer["w_gate"] = dense((F, D), D)
            layer["w_up"] = dense((F, D), D)
            layer["w_down"] = dense((D, F), F)
        params["layers"].append(layer)
    return params


def _norm(x, p, cfg: Config):
    if cfg.norm == "rmsnorm":
        return ops.rms_norm(x, p["w"], eps=cfg.norm_eps)
    return ops.layer_norm(x, (cfg.dim,), p["w"], p["b"], eps=cfg.norm_eps)


def _rope_tables(cfg: Config, T: int, dtype):
    rot = int(cfg.head_dim * cfg.rotary_pct) // 2 * 2
    pos = ops.convert_element_type(ops.arange(T), dtypes.float32)
    idx = ops.convert_element_type(ops.arange(rot // 2), dtypes.float32)
    inv_freq = ops.pow(cfg.rope_theta, ops.true_divide(ops.mul(idx, -2.0), float(rot)))
    angles = ops.mul(ops.unsqueeze(pos, 1), ops.unsqueeze(inv_freq, 0))
    return (ops.convert_element_type(ops.cos(angles), dtype),
            ops.convert_element_type(ops.sin(angles), dtype), rot)


def _apply_rope(x, cos, sin, rot: int):
    """Partial rotary (NeoX-style half rotation on the first ``rot`` dims)."""
    if rot == 0:
        return x
    xr = x[..., :rot]
    rest = x[..., rot:]
    x1 = xr[..., : rot // 2]
    x2 = xr[..., rot // 2:]
    r1 = ops.sub(ops.mul(x1, cos), ops.mul(x2, sin))
    r2 = ops.add(ops.mul(x2, cos), ops.mul(x1, sin))
    out = ops.cat([r1, r2], -1)
    if rot == x.shape[-1]:
        return out
    return ops.cat([out, rest], -1)


def _attention(x, layer, cfg: Config, rope):
    B, T, _ = x.shape
    hd = cfg.head_dim
    H, KV = cfg.n_heads, cfg.kv_heads
    q = ops.linear(x, layer["wq"], layer.get("bq"))
    k = ops.linear(x, layer["wk"], layer.get("bk"))
    v = ops.linear(x, layer["wv"], layer.get("bv"))
    q = ops.transpose(ops.reshape(q, (B, T, H, hd)), (0, 2, 1, 3))
    k = ops.transpose(ops.reshape(k, (B, T, KV, hd)), (0, 2, 1, 3))
    v = ops.transpose(ops.reshape(v, (B, T, KV, hd)), (0, 2, 1, 3))
    if rope is not None:
        cos, sin, rot = rope
        q = _apply_rope(q, cos, sin, rot)
        k = _apply_rope(k, cos, sin, rot)
    if H != KV:  # MQA / GQA
        rep = H // KV
        k = ops.reshape(ops.expand(ops.unsqueeze(k, 2), (B, KV, rep, T, hd)), (B, H, T, hd))
        v = ops.reshape(ops.expand(ops.unsqueeze(v, 2), (B, KV, rep, T, hd)), (B, H, T, hd))
    attn = ops.scaled_dot_product_attention(q, k, v, is_causal=True)
    attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, H * hd))
    return ops.linear(attn, layer["wo"])


def _mlp(x, layer, cfg: Config):
    if cfg.mlp == "gelu":
        h = ops.gelu(ops.linear(x, layer["w_fc"], layer.get("b_fc")))
        return ops.linear(h, layer["w_proj"], layer.get("b_proj"))
    act = ops.silu if cfg.mlp == "swiglu" else ops.gelu
    gate = act(ops.linear(x, layer["w_gate"]))
    up = ops.linear(x, layer["w_up"])
    return ops.linear(ops.mul(gate, up), layer["w_down"])


def forward(params, tokens, cfg: Config):
    B, T = tokens.shape
    h = ops.embedding(tokens, params["wte"])
    if cfg.emb_scale:
        h = ops.mul(h, math.sqrt(cfg.dim))
    if cfg.pos == "learned":
        h = ops.add(h, params["wpe"][0:T])
    rope = _rope_tables(cfg, T, h.dtype) if cfg.pos == "rope" else None

    for layer in params["layers"]:
        if cfg.parallel_residual:
            # NeoX/Falcon: one shared norm feeds both attn and MLP
            n1 = _norm(h, layer["norm1"], cfg)
            h = ops.add(h, ops.add(_attention(n1, layer, cfg, rope), _mlp(n1, layer, cfg)))
        else:
            h = ops.add(h, _attention(_norm(h, layer["norm1"], cfg), layer, cfg, rope))
            h = ops.add(h, _mlp(_norm(h, layer["norm2"], cfg), layer, cfg))

    h = _norm(h, params["norm_f"], cfg)
    head_w = params["wte"] if cfg.tie_embedding else params["lm_head"]
    return ops.linear(h, head_w)


def loss_fn(params, tokens, targets, cfg: Config):
    logits = forward(params, tokens, cfg)
    B, T, V = logits.shape
    logits = ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32)
    return ops.cross_entropy(logits, ops.reshape(targets, (B * T,)))


def num_params(cfg: Config, n_layers: int | None = None) -> int:
    import jax
    import numpy as np

    n = n_layers if n_layers is not None else cfg.n_layers
    shapes = jax.eval_shape(lambda: init_params(cfg, seed=0, scale_layers=n))
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(shapes))

"""Mixtral-style mixture-of-experts transformer.

BASELINE config 5 ("Mixtral-8x7B expert-parallel — new capability, absent
from reference"). Architecture: Llama attention blocks + top-k routed SwiGLU
experts with GShard-style capacity-based dense dispatch (static shapes for
XLA): tokens → one-hot dispatch (S, E, C) via cumsum positions → batched
per-expert matmuls on the MXU → weighted combine. Under an active
expert-parallel scope the (E, C, d) slot tensor is exchanged with
``all_to_all`` so each rank runs only its local experts.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

from thunder_tpu import ops
from thunder_tpu.core import dtypes, prims
from thunder_tpu.models import llama as _llama


@dataclass(frozen=True)
class MixtralConfig:
    name: str = "tiny-moe"
    vocab_size: int = 512
    dim: int = 64
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int | None = None
    intermediate_size: int = 128
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    # dropless: per-expert capacity = S (the static worst case — a token can
    # reach an expert at most once), so NO token is ever dropped. Memory for
    # the dispatch tensors grows from O(S·E·S·cf·k/E)=O(S²·cf·k) to O(S²·E);
    # the TPU-idiomatic middle ground is a measured capacity_factor (see
    # capacity_sweep / MIXTRAL_EP.md). Ragged MegaBlocks-style block-sparse
    # dispatch needs a Pallas kernel and stays future work.
    dropless: bool = False
    max_seq_len: int = 256
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    router_aux_coef: float = 0.01
    dtype: dtypes.dtype = dtypes.float32

    @property
    def kv_heads(self):
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self):
        return self.dim // self.n_heads


CONFIGS = {
    "tiny-moe": MixtralConfig(),
    "mixtral-8x7b": MixtralConfig(
        name="mixtral-8x7b", vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, intermediate_size=14336, n_experts=8, top_k=2,
        max_seq_len=4096, rope_theta=1e6, dtype=dtypes.bfloat16),
}

EP_PATTERNS = (r"\['we_gate'\]", r"\['we_up'\]", r"\['we_down'\]")


def init_params(cfg: MixtralConfig, seed: int = 0, scale_layers: int | None = None):
    import jax
    import jax.numpy as jnp

    n_layers = scale_layers if scale_layers is not None else cfg.n_layers
    jd = cfg.dtype.jax
    key = jax.random.PRNGKey(seed)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(jd)

    keys = iter(jax.random.split(key, 4 + n_layers * 16))
    kv_dim = cfg.kv_heads * cfg.head_dim
    params = {
        "tok_embedding": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "norm_f": jnp.ones((cfg.dim,), jd),
        "lm_head": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": [],
    }
    E, I, D = cfg.n_experts, cfg.intermediate_size, cfg.dim
    for _ in range(n_layers):
        params["layers"].append({
            "attn_norm": jnp.ones((D,), jd),
            "wq": dense(next(keys), (D, D), D),
            "wk": dense(next(keys), (kv_dim, D), D),
            "wv": dense(next(keys), (kv_dim, D), D),
            "wo": dense(next(keys), (D, D), D),
            "mlp_norm": jnp.ones((D,), jd),
            "router": dense(next(keys), (E, D), D),
            "we_gate": dense(next(keys), (E, I, D), D),
            "we_up": dense(next(keys), (E, I, D), D),
            "we_down": dense(next(keys), (E, D, I), I),
        })
    return params


def moe_ffn(x, router_w, we_gate, we_up, we_down, cfg: MixtralConfig,
            return_metrics: bool = False):
    """x: (S, D) flattened tokens. Returns ``(out (S, D), aux_loss scalar)``,
    plus a metrics dict (tokens kept per expert, assignment drop rate, router
    load fractions) when ``return_metrics``."""
    from thunder_tpu.distributed import current_ep
    from thunder_tpu.distributed import prims as dist_prims

    S, D = x.shape
    E = router_w.shape[0]
    k = cfg.top_k
    if cfg.dropless:
        C = S  # static worst case: every token can reach an expert at most once
    else:
        C = max(1, min(S, int(math.ceil(S * cfg.capacity_factor * k / E))))

    logits = ops.linear(ops.convert_element_type(x, dtypes.float32),
                        ops.convert_element_type(router_w, dtypes.float32))  # (S, E)
    probs = ops.softmax(logits, -1)
    topv, topi = ops.topk(probs, k, -1)  # (S, k)
    topv = ops.true_divide(topv, ops.sum(topv, -1, keepdim=True))

    # Capacity-based dispatch by INDEX, not one-hot einsum (r5, VERDICT r4
    # #5): the position of each token in its expert's slot queue comes from
    # the same GShard cumsum, but tokens move via ONE gather into (E, C)
    # slots and ONE gather back — the old (S, E, C) dispatch/combine
    # einsums spent 8·S·E·C·D matmul flops (fwd+bwd) moving data the MXU
    # never needed to touch. Routing (which tokens go where) is identical.
    counts = ops.zeros((E,), dtype=dtypes.float32)
    flat_pos = []   # per assignment j: token s -> slot e*C+pos, sentinel E*C
    for j in range(k):
        m = ops.convert_element_type(ops.one_hot(topi[:, j], E), dtypes.float32)  # (S, E)
        pos = ops.add(ops.sub(ops.cumsum(m, 0), m), ops.expand_to(counts, m.shape))
        keep = ops.mul(m, ops.convert_element_type(ops.lt(pos, float(C)), dtypes.float32))
        counts = ops.add(counts, ops.sum(keep, 0))
        pos_j = ops.sum(ops.mul(pos, m), -1)                       # (S,) queue slot
        kept_j = ops.gt(ops.sum(keep, -1), 0.0)                    # (S,) bool
        e_j = ops.convert_element_type(topi[:, j], dtypes.int32)
        fp = ops.add(ops.mul(e_j, C), ops.convert_element_type(pos_j, dtypes.int32))
        flat_pos.append(ops.where(kept_j, fp, ops.full((S,), E * C, dtype=dtypes.int32)))

    # load-balancing auxiliary loss (Switch/Mixtral style). Under expert
    # parallelism the batch is sharded, and the loss is NONLINEAR in the
    # router statistics — the fractions must be averaged over the ep axis
    # BEFORE the product, or per-shard aux averaged afterwards diverges from
    # the single-device value (measured 0.008 on a 6.66 loss)
    frac_tokens = ops.mean(ops.convert_element_type(
        ops.one_hot(topi[:, 0], E), dtypes.float32), 0)
    frac_probs = ops.mean(probs, 0)
    ep = current_ep()
    if ep is not None:
        axis, n = ep
        frac_tokens = ops.true_divide(
            dist_prims.wait(dist_prims.all_reduce(frac_tokens, axis, "sum")), float(n))
        frac_probs = ops.true_divide(
            dist_prims.wait(dist_prims.all_reduce(frac_probs, axis, "sum")), float(n))
    aux = ops.mul(ops.sum(ops.mul(frac_tokens, frac_probs)), float(E) * cfg.router_aux_coef)

    xf = ops.convert_element_type(x, dtypes.float32)
    # MIXTRAL_FORCE_EINSUM=1: debug/bench knob to run the EP einsum dispatch
    # single-device (used by the r5 flop A/B in MIXTRAL_EP.md)
    _force_einsum = os.environ.get("MIXTRAL_FORCE_EINSUM") == "1"
    if not _force_einsum:
        # scatter token ids into the slot table (slots are unique by
        # construction — the cumsum assigns each (expert, position) once;
        # only the sentinel overflow bin sees duplicate writes and is never
        # read), then ONE row gather builds the expert inputs. Dropped
        # slots read the zero pad row.
        slot_tokens = ops.full((E * C + 1,), S, dtype=dtypes.int32)
        token_ids = ops.arange(S, dtype=dtypes.int32)
        for fp in flat_pos:
            slot_tokens = ops.index_put(slot_tokens, (fp,), token_ids)
        x_padded = ops.cat([xf, ops.zeros((1, D), dtype=dtypes.float32)], 0)
        expert_in = ops.reshape(
            prims.take(x_padded, ops.narrow(slot_tokens, 0, 0, E * C), 0), (E, C, D))
    else:
        # one-hot dispatch einsum, kept ONLY as the MIXTRAL_FORCE_EINSUM=1
        # A/B control (MIXTRAL_EP.md): since r5 the spec rules express the
        # index dispatch's data-dependent permutation as device-varying
        # fuzzy state, so the gather path above runs under EP too
        dispatch = None  # (S, E, C)
        combine = None
        for j, fp in enumerate(flat_pos):
            kept = ops.convert_element_type(ops.lt(fp, E * C), dtypes.float32)
            pos_oh = ops.convert_element_type(ops.one_hot(
                ops.remainder(fp, C), C), dtypes.float32)           # (S, C)
            e_oh = ops.convert_element_type(ops.one_hot(
                ops.convert_element_type(ops.floor_divide(fp, C), dtypes.int32),
                E), dtypes.float32)                                  # (S, E)
            disp_j = ops.mul(ops.mul(ops.unsqueeze(e_oh, -1),
                                     ops.unsqueeze(pos_oh, 1)),
                             ops.reshape(kept, (S, 1, 1)))
            comb_j = ops.mul(disp_j, ops.expand_to(
                ops.reshape(topv[:, j], (S, 1, 1)), disp_j.shape))
            dispatch = disp_j if dispatch is None else ops.add(dispatch, disp_j)
            combine = comb_j if combine is None else ops.add(combine, comb_j)
        expert_in = prims.dot_general(dispatch, xf, contract_dims=((0,), (0,)))  # (E, C, D)

    if ep is not None:
        axis, n = ep
        # rank-local slots for all experts -> all slots for local experts
        expert_in = dist_prims.wait(dist_prims.all_to_all(expert_in, axis, 0, 1, n))  # (E/n, C*n, D)

    weg = ops.convert_element_type(we_gate, dtypes.float32)
    weu = ops.convert_element_type(we_up, dtypes.float32)
    wed = ops.convert_element_type(we_down, dtypes.float32)
    gate = ops.silu(prims.dot_general(expert_in, weg, contract_dims=((2,), (2,)),
                                      batch_dims=((0,), (0,))))  # (E?, C?, I)
    up = prims.dot_general(expert_in, weu, contract_dims=((2,), (2,)), batch_dims=((0,), (0,)))
    expert_out = prims.dot_general(ops.mul(gate, up), wed, contract_dims=((2,), (2,)),
                                   batch_dims=((0,), (0,)))  # (E?, C?, D)

    if ep is not None:
        axis, n = ep
        expert_out = dist_prims.wait(dist_prims.all_to_all(expert_out, axis, 1, 0, n))  # (E, C, D)

    if not _force_einsum:
        # combine: each token gathers its k slots back, weighted by its gate
        eo_flat = ops.cat([ops.reshape(expert_out, (E * C, D)),
                           ops.zeros((1, D), dtype=dtypes.float32)], 0)
        out = None
        for j in range(k):
            contrib = ops.mul(prims.take(eo_flat, flat_pos[j], 0),
                              ops.unsqueeze(topv[:, j], -1))        # (S, D)
            out = contrib if out is None else ops.add(out, contrib)
    else:
        out = prims.dot_general(combine, expert_out,
                                contract_dims=(((1, 2)), ((0, 1))))  # (S, D)
    out = ops.convert_element_type(out, x.dtype)
    if return_metrics:
        total_assignments = float(S * k)
        metrics = {
            "tokens_per_expert": counts,                       # kept, (E,)
            "drop_rate": ops.sub(1.0, ops.true_divide(
                ops.sum(counts, None), total_assignments)),    # scalar
            "router_load": frac_probs,                         # (E,) mean prob
            "capacity": C,
        }
        return out, aux, metrics
    return out, aux


def forward(params, tokens, cfg: MixtralConfig, return_aux: bool = False,
            return_metrics: bool = False, remat: bool = False,
            skip_head: bool = False):
    """``remat=True`` checkpoints each block (attention + MoE) so only the
    (B*T, dim) block inputs are saved — the expert-MLP intermediates at
    (tokens, 14336) f32 are what blow HBM at 8x7B scale. ``skip_head=True``
    returns the pre-lm_head hidden states (the fused-loss path)."""
    B, T = tokens.shape
    h = ops.embedding(tokens, params["tok_embedding"])
    cos, sin = _llama._rope_cos_sin(cfg, T, h.dtype)
    hd = cfg.head_dim
    n_rep = cfg.n_heads // cfg.kv_heads
    aux_total = None
    layer_metrics = []

    def block(h, attn_norm, wq, wk, wv, wo, mlp_norm, router,
              we_gate, we_up, we_down):
        x = ops.rms_norm(h, attn_norm, eps=cfg.norm_eps)
        q = ops.linear(x, wq)
        kk = ops.linear(x, wk)
        v = ops.linear(x, wv)
        q = ops.transpose(ops.reshape(q, (B, T, cfg.n_heads, hd)), (0, 2, 1, 3))
        kk = ops.transpose(ops.reshape(kk, (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        v = ops.transpose(ops.reshape(v, (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        q = _llama._apply_rope(q, cos, sin)
        kk = _llama._apply_rope(kk, cos, sin)
        if n_rep > 1:
            kk = ops.reshape(ops.expand(ops.unsqueeze(kk, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                             (B, cfg.n_heads, T, hd))
            v = ops.reshape(ops.expand(ops.unsqueeze(v, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                            (B, cfg.n_heads, T, hd))
        attn = ops.scaled_dot_product_attention(q, kk, v, is_causal=True)
        attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, cfg.n_heads * hd))
        h = ops.add(h, ops.linear(attn, wo))

        x = ops.rms_norm(h, mlp_norm, eps=cfg.norm_eps)
        moe_out, aux = moe_ffn(ops.reshape(x, (B * T, cfg.dim)), router,
                               we_gate, we_up, we_down, cfg)
        return ops.add(h, ops.reshape(moe_out, (B, T, cfg.dim))), aux

    def block_with_metrics(h, layer):
        # diagnostics path (un-checkpointed): same math as ``block`` but
        # moe_ffn also returns per-layer routing metrics
        x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
        q = ops.transpose(ops.reshape(ops.linear(x, layer["wq"]),
                                      (B, T, cfg.n_heads, hd)), (0, 2, 1, 3))
        kk = ops.transpose(ops.reshape(ops.linear(x, layer["wk"]),
                                       (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        v = ops.transpose(ops.reshape(ops.linear(x, layer["wv"]),
                                      (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
        q = _llama._apply_rope(q, cos, sin)
        kk = _llama._apply_rope(kk, cos, sin)
        if n_rep > 1:
            kk = ops.reshape(ops.expand(ops.unsqueeze(kk, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                             (B, cfg.n_heads, T, hd))
            v = ops.reshape(ops.expand(ops.unsqueeze(v, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                            (B, cfg.n_heads, T, hd))
        attn = ops.scaled_dot_product_attention(q, kk, v, is_causal=True)
        attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, cfg.n_heads * hd))
        h = ops.add(h, ops.linear(attn, layer["wo"]))
        x = ops.rms_norm(h, layer["mlp_norm"], eps=cfg.norm_eps)
        moe_out, aux, metrics = moe_ffn(
            ops.reshape(x, (B * T, cfg.dim)), layer["router"],
            layer["we_gate"], layer["we_up"], layer["we_down"], cfg,
            return_metrics=True)
        return ops.add(h, ops.reshape(moe_out, (B, T, cfg.dim))), aux, metrics

    for layer in params["layers"]:
        if return_metrics:
            h, aux, metrics = block_with_metrics(h, layer)
            layer_metrics.append(metrics)
        else:
            fn = block
            if remat:
                import thunder_tpu as tt

                fn = tt.checkpoint(block)
            h, aux = fn(h, layer["attn_norm"], layer["wq"], layer["wk"],
                        layer["wv"], layer["wo"], layer["mlp_norm"],
                        layer["router"], layer["we_gate"], layer["we_up"],
                        layer["we_down"])
        aux_total = aux if aux_total is None else ops.add(aux_total, aux)

    h = ops.rms_norm(h, params["norm_f"], eps=cfg.norm_eps)
    if skip_head:
        return h, aux_total
    logits = ops.linear(h, params["lm_head"])
    if return_metrics:
        return logits, aux_total, layer_metrics
    if return_aux:
        return logits, aux_total
    return logits


def loss_fn(params, tokens, targets, cfg: MixtralConfig, remat: bool = False):
    logits, aux = forward(params, tokens, cfg, return_aux=True, remat=remat)
    B, T, V = logits.shape
    ce = ops.cross_entropy(ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32),
                           ops.reshape(targets, (B * T,)))
    return ops.add(ce, aux)


def fused_loss_fn(params, tokens, targets, cfg: MixtralConfig,
                  remat: bool = False):
    """Chunked-vocab loss (lm_head fused into the CE — the (B*T, vocab)
    f32 logits are never materialized) + optional per-block remat: the
    memory shape that fits Mixtral-8x7B training on real HBM budgets
    (NORTHSTAR.md)."""
    from thunder_tpu.ops import nn as tnn

    B, T = tokens.shape
    h, aux = forward(params, tokens, cfg, remat=remat, skip_head=True)
    out = tnn.fused_linear_cross_entropy(
        ops.reshape(h, (B * T, cfg.dim)), params["lm_head"],
        ops.reshape(targets, (B * T,)))
    ce = out[0] if isinstance(out, tuple) else out
    return ops.add(ce, aux)


def expert_utilization(params, tokens, cfg: MixtralConfig):
    """Per-layer expert routing report (VERDICT r2 item 10): tokens kept per
    expert, assignment drop rate, router load fractions, fraction of experts
    used, and max/mean load imbalance. Compiled+run once on ``tokens``."""
    import numpy as np

    import thunder_tpu as tt

    jf = tt.jit(lambda p, t: forward(p, t, cfg, return_metrics=True))
    _logits, _aux, metrics = jf(params, tokens)
    report = []
    for m in metrics:
        tpe = np.asarray(m["tokens_per_expert"])
        report.append({
            "tokens_per_expert": tpe.astype(int).tolist(),
            "drop_rate": float(np.asarray(m["drop_rate"])),
            "router_load": np.round(np.asarray(m["router_load"]), 4).tolist(),
            "capacity": int(m["capacity"]),
            "expert_usage": float((tpe > 0).mean()),
            "load_imbalance": float(tpe.max() / max(tpe.mean(), 1e-9)),
        })
    return report


def capacity_sweep(params, tokens, cfg: MixtralConfig,
                   factors=(1.0, 1.25, 1.5, 2.0, 4.0)):
    """Max per-layer assignment drop rate for each capacity factor (plus the
    dropless mode as reference) — the tuning table MIXTRAL_EP.md commits."""
    import dataclasses

    out = {}
    for f in factors:
        c2 = dataclasses.replace(cfg, capacity_factor=f, dropless=False)
        rep = expert_utilization(params, tokens, c2)
        out[f] = max(r["drop_rate"] for r in rep)
    c_dropless = dataclasses.replace(cfg, dropless=True)
    rep = expert_utilization(params, tokens, c_dropless)
    out["dropless"] = max(r["drop_rate"] for r in rep)
    return out

"""nanoGPT-style GPT-2 model (model-zoo parity with the reference's
self-contained ``thunder/tests/nanogpt_model.py`` — fresh functional
implementation: learned position embeddings, pre-LN blocks, GELU MLP,
optional weight tying)."""

from __future__ import annotations

from dataclasses import dataclass

from thunder_tpu import ops
from thunder_tpu.core import dtypes


@dataclass(frozen=True)
class GPTConfig:
    name: str = "gpt2-tiny"
    vocab_size: int = 512
    block_size: int = 128
    n_layer: int = 4
    n_head: int = 4
    n_embd: int = 64
    dropout: float = 0.0
    dtype: dtypes.dtype = dtypes.float32


CONFIGS = {
    "gpt2-tiny": GPTConfig(),
    "gpt2": GPTConfig(name="gpt2", vocab_size=50257, block_size=1024, n_layer=12,
                      n_head=12, n_embd=768),
    "gpt2-xl": GPTConfig(name="gpt2-xl", vocab_size=50257, block_size=1024, n_layer=48,
                         n_head=25, n_embd=1600, dtype=dtypes.bfloat16),
}


def init_params(cfg: GPTConfig, seed: int = 0, scale_layers: int | None = None):
    import jax
    import jax.numpy as jnp

    n_layer = scale_layers if scale_layers is not None else cfg.n_layer
    jd = cfg.dtype.jax
    key = jax.random.PRNGKey(seed)
    D = cfg.n_embd

    def dense(key, shape, std=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(jd)

    keys = iter(jax.random.split(key, 4 + n_layer * 4))
    params = {
        "wte": dense(next(keys), (cfg.vocab_size, D)),
        "wpe": dense(next(keys), (cfg.block_size, D)),
        "ln_f": {"w": jnp.ones((D,), jd), "b": jnp.zeros((D,), jd)},
        "blocks": [],
    }
    for _ in range(n_layer):
        params["blocks"].append({
            "ln1": {"w": jnp.ones((D,), jd), "b": jnp.zeros((D,), jd)},
            "attn_qkv": {"w": dense(next(keys), (3 * D, D)), "b": jnp.zeros((3 * D,), jd)},
            "attn_proj": {"w": dense(next(keys), (D, D)), "b": jnp.zeros((D,), jd)},
            "ln2": {"w": jnp.ones((D,), jd), "b": jnp.zeros((D,), jd)},
            "mlp_fc": {"w": dense(next(keys), (4 * D, D)), "b": jnp.zeros((4 * D,), jd)},
            "mlp_proj": {"w": dense(next(keys), (D, 4 * D)), "b": jnp.zeros((D,), jd)},
        })
    return params


def forward(params, tokens, cfg: GPTConfig, training: bool = False):
    B, T = tokens.shape
    D, H = cfg.n_embd, cfg.n_head
    hd = D // H

    tok = ops.embedding(tokens, params["wte"])  # (B, T, D)
    pos = ops.embedding(ops.arange(T), params["wpe"])  # (T, D)
    h = ops.add(tok, pos)
    if training and cfg.dropout > 0:
        h = ops.dropout(h, cfg.dropout)

    for blk in params["blocks"]:
        x = ops.layer_norm(h, (D,), blk["ln1"]["w"], blk["ln1"]["b"])
        qkv = ops.linear(x, blk["attn_qkv"]["w"], blk["attn_qkv"]["b"])  # (B, T, 3D)
        q, k, v = ops.split(qkv, D, dim=-1)
        q = ops.transpose(ops.reshape(q, (B, T, H, hd)), (0, 2, 1, 3))
        k = ops.transpose(ops.reshape(k, (B, T, H, hd)), (0, 2, 1, 3))
        v = ops.transpose(ops.reshape(v, (B, T, H, hd)), (0, 2, 1, 3))
        att = ops.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=cfg.dropout if training else 0.0)
        att = ops.reshape(ops.transpose(att, (0, 2, 1, 3)), (B, T, D))
        att = ops.linear(att, blk["attn_proj"]["w"], blk["attn_proj"]["b"])
        if training and cfg.dropout > 0:
            att = ops.dropout(att, cfg.dropout)
        h = ops.add(h, att)

        x = ops.layer_norm(h, (D,), blk["ln2"]["w"], blk["ln2"]["b"])
        m = ops.gelu(ops.linear(x, blk["mlp_fc"]["w"], blk["mlp_fc"]["b"]), approximate="tanh")
        m = ops.linear(m, blk["mlp_proj"]["w"], blk["mlp_proj"]["b"])
        if training and cfg.dropout > 0:
            m = ops.dropout(m, cfg.dropout)
        h = ops.add(h, m)

    h = ops.layer_norm(h, (D,), params["ln_f"]["w"], params["ln_f"]["b"])
    # weight-tied head (GPT-2)
    logits = ops.linear(h, params["wte"])
    return logits


def loss_fn(params, tokens, targets, cfg: GPTConfig, training: bool = False):
    logits = forward(params, tokens, cfg, training=training)
    B, T, V = logits.shape
    return ops.cross_entropy(
        ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32),
        ops.reshape(targets, (B * T,)))

"""ResNet-style CNN model family (beyond the reference's model zoo, which is
transformer-only — added once CONVOLUTION grew a VJP so conv nets train
end-to-end; exercises conv/pool/batch-norm through the whole trace pipeline).

TPU-first design notes:
- purely functional: batch-norm running statistics are explicit state threaded
  through the step (``forward(..., state) -> (logits, new_state)``), the same
  state-threading discipline the FP8 amax history uses — no module mutation.
- NCHW layout with channel counts that keep XLA's conv tiling on the MXU;
  bf16-friendly (stats accumulate in f32).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from thunder_tpu import ops
from thunder_tpu.core import dtypes


@dataclass(frozen=True)
class ResNetConfig:
    name: str = "resnet-tiny"
    num_classes: int = 10
    in_channels: int = 3
    width: int = 8                      # channels of the first stage
    stage_blocks: tuple = (1, 1, 1)     # residual blocks per stage (stride-2 between)
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    dtype: dtypes.dtype = dtypes.float32


CONFIGS = {
    "resnet-tiny": ResNetConfig(),
    "resnet18": ResNetConfig(name="resnet18", num_classes=1000, width=64,
                             stage_blocks=(2, 2, 2, 2)),
    "resnet34": ResNetConfig(name="resnet34", num_classes=1000, width=64,
                             stage_blocks=(3, 4, 6, 3)),
}


def _conv_init(key, cout, cin, k):
    import jax
    import jax.numpy as jnp

    fan_in = cin * k * k
    std = (2.0 / fan_in) ** 0.5
    return jax.random.normal(key, (cout, cin, k, k), jnp.float32) * std


def init_bn_state(cfg: ResNetConfig):
    """Identity batch-norm statistics (zeros mean / ones var) — the cheap
    stateless-inference fallback; no RNG or weight allocation."""
    import jax.numpy as jnp

    def bn_state(c):
        return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}

    state = {"stem": bn_state(cfg.width), "stages": []}
    for si, n_blocks in enumerate(cfg.stage_blocks):
        c_out = cfg.width * (2 ** si)
        state["stages"].append([{"bn1": bn_state(c_out), "bn2": bn_state(c_out)}
                                for _ in range(n_blocks)])
    return state


def init_params(cfg: ResNetConfig, seed: int = 0):
    """Returns (params, bn_state). ``bn_state`` holds running mean/var per
    norm layer — thread it through ``forward`` during training."""
    import jax
    import jax.numpy as jnp

    jd = cfg.dtype.jax
    key = jax.random.PRNGKey(seed)
    n_convs = 1 + sum(cfg.stage_blocks) * 2 + sum(1 for i in range(len(cfg.stage_blocks)) if i > 0)
    keys = iter(jax.random.split(key, n_convs + 1))

    def bn(c):
        return {"scale": jnp.ones((c,), jd), "bias": jnp.zeros((c,), jd)}

    def bn_state(c):
        return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}

    params = {"stem": {"w": _conv_init(next(keys), cfg.width, cfg.in_channels, 3).astype(jd),
                       "bn": bn(cfg.width)},
              "stages": [], "fc": None}
    state = {"stem": bn_state(cfg.width), "stages": []}

    c_in = cfg.width
    for si, n_blocks in enumerate(cfg.stage_blocks):
        c_out = cfg.width * (2 ** si)
        stage_p, stage_s = [], []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            blk = {"conv1": {"w": _conv_init(next(keys), c_out, c_in, 3).astype(jd), "bn": bn(c_out)},
                   "conv2": {"w": _conv_init(next(keys), c_out, c_out, 3).astype(jd), "bn": bn(c_out)},
                   "down": None}
            sblk = {"bn1": bn_state(c_out), "bn2": bn_state(c_out)}
            if stride != 1 or c_in != c_out:
                blk["down"] = {"w": _conv_init(next(keys), c_out, c_in, 1).astype(jd)}
            stage_p.append(blk)
            stage_s.append(sblk)
            c_in = c_out
        params["stages"].append(stage_p)
        state["stages"].append(stage_s)

    fc_key = next(keys)
    params["fc"] = {"w": (jax.random.normal(fc_key, (cfg.num_classes, c_in), jnp.float32)
                          * (1.0 / c_in) ** 0.5).astype(jd),
                    "b": jnp.zeros((cfg.num_classes,), jd)}
    return params, state


def _batch_norm(x, p, s, cfg, training):
    """Functional batch-norm; returns (normalized, new_state)."""
    if training:
        xf = ops.convert_element_type(x, dtypes.float32)
        mean = ops.mean(xf, dim=(0, 2, 3))
        var = ops.var(xf, dim=(0, 2, 3), correction=0)
        m = cfg.bn_momentum
        new_s = {"mean": ops.add(ops.mul(s["mean"], 1.0 - m), ops.mul(mean, m)),
                 "var": ops.add(ops.mul(s["var"], 1.0 - m), ops.mul(var, m))}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = ops.rsqrt(ops.add(var, cfg.bn_eps))
    scale = ops.mul(p["scale"], ops.convert_element_type(inv, x.dtype))
    shift = ops.sub(p["bias"], ops.mul(ops.convert_element_type(mean, x.dtype), scale))

    def bcast(v):
        return ops.reshape(v, (1, -1, 1, 1))

    return ops.add(ops.mul(x, bcast(scale)), bcast(shift)), new_s


def forward(params, x, cfg: ResNetConfig, state=None, training: bool = False):
    """x: (N, C, H, W) -> (logits, new_state)."""
    if state is None:
        training = False
        state = init_bn_state(cfg)  # inference fallback: identity stats
    new_state = {"stem": None, "stages": []}

    h = ops.conv2d(x, params["stem"]["w"], stride=1, padding=1)
    h, new_state["stem"] = _batch_norm(h, params["stem"]["bn"], state["stem"], cfg, training)
    h = ops.relu(h)

    for si, (stage_p, stage_s) in enumerate(zip(params["stages"], state["stages"])):
        ns_stage = []
        for bi, (blk, sblk) in enumerate(zip(stage_p, stage_s)):
            stride = 2 if (si > 0 and bi == 0) else 1
            r = h
            o = ops.conv2d(h, blk["conv1"]["w"], stride=stride, padding=1)
            o, ns1 = _batch_norm(o, blk["conv1"]["bn"], sblk["bn1"], cfg, training)
            o = ops.relu(o)
            o = ops.conv2d(o, blk["conv2"]["w"], stride=1, padding=1)
            o, ns2 = _batch_norm(o, blk["conv2"]["bn"], sblk["bn2"], cfg, training)
            if blk["down"] is not None:
                r = ops.conv2d(r, blk["down"]["w"], stride=stride, padding=0)
            h = ops.relu(ops.add(o, r))
            ns_stage.append({"bn1": ns1, "bn2": ns2})
        new_state["stages"].append(ns_stage)

    h = ops.mean(h, dim=(2, 3))  # global average pool
    logits = ops.add(ops.matmul(h, ops.transpose(params["fc"]["w"], (1, 0))), params["fc"]["b"])
    return logits, new_state


def loss_fn(params, x, targets, cfg: ResNetConfig, state=None, training: bool = True):
    """Cross-entropy loss; returns (loss, new_state)."""
    from thunder_tpu.ops import nn

    logits, new_state = forward(params, x, cfg, state=state, training=training)
    return nn.cross_entropy(ops.convert_element_type(logits, dtypes.float32), targets), new_state

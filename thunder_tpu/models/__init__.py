"""Model zoo: self-contained model definitions written against
``thunder_tpu.ops`` (reference parity: ``thunder/tests/nanogpt_model.py``,
``litgpt_model.py``, ``llama2_model.py`` — fresh implementations)."""

from thunder_tpu.models import llama, mixtral, nanogpt  # noqa: F401
from thunder_tpu.models import gpt  # noqa: F401
from thunder_tpu.models import seq2seq  # noqa: F401

"""Llama-family transformer (Llama 2 / Llama 3 / tiny configs), written
functionally against ``thunder_tpu.ops``.

Covers the reference's model-zoo role (``thunder/tests/llama2_model.py``,
``litgpt`` GPT in ``thunder/tests/litgpt_model.py``) with the BASELINE.md
configs: tiny-stories Llama (config 1), Llama-2-7B (configs 2-3),
Llama-3-8B with GQA (config 4). Pure functions over a params pytree — the
TPU-first shape: the whole train step (fwd+bwd+optimizer) compiles into one
XLA program, and the distributed transforms shard the params pytree.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from thunder_tpu import ops
from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.baseutils import check


@dataclass(frozen=True)
class LlamaConfig:
    name: str = "tiny"
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int | None = None  # GQA when < n_heads
    intermediate_size: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: dtypes.dtype = dtypes.float32
    head_dim_override: int | None = None  # set by tensor-parallel local configs

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.dim // self.n_heads


CONFIGS = {
    # llama2.c tiny-stories scale (BASELINE config 1)
    "tiny": LlamaConfig(name="tiny", vocab_size=512, dim=64, n_layers=4, n_heads=4,
                        intermediate_size=176, max_seq_len=256),
    "tiny-gqa": LlamaConfig(name="tiny-gqa", vocab_size=512, dim=64, n_layers=4, n_heads=4,
                            n_kv_heads=2, intermediate_size=176, max_seq_len=256),
    # 8-way tensor-parallel smoke scale: every sharded dim (heads, kv heads,
    # intermediate) divides by 8, so the CPU 8-device mesh splits it cleanly
    "tiny-tp": LlamaConfig(name="tiny-tp", vocab_size=512, dim=64, n_layers=4, n_heads=8,
                           n_kv_heads=8, intermediate_size=192, max_seq_len=256),
    "llama2-7b": LlamaConfig(name="llama2-7b", vocab_size=32000, dim=4096, n_layers=32,
                             n_heads=32, intermediate_size=11008, max_seq_len=4096,
                             dtype=dtypes.bfloat16),
    "llama2-7b-bench": LlamaConfig(name="llama2-7b-bench", vocab_size=32000, dim=4096,
                                   n_layers=32, n_heads=32, intermediate_size=11008,
                                   max_seq_len=2048, dtype=dtypes.bfloat16),
    "llama3-8b": LlamaConfig(name="llama3-8b", vocab_size=128256, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, intermediate_size=14336,
                             max_seq_len=8192, rope_theta=500000.0, dtype=dtypes.bfloat16),
    # bench variant: same per-layer arithmetic (GQA 32/8 heads, MLP 14336);
    # vocab capped at 32k and seq at 2048 so a scaled-layer slice + full
    # AdamW state fits one 16GB chip (the 128k-vocab embed+head alone is
    # 1.05B params — the GQA attention/MLP geometry is what this measures)
    "llama3-8b-bench": LlamaConfig(name="llama3-8b-bench", vocab_size=32000, dim=4096,
                                   n_layers=32, n_heads=32, n_kv_heads=8,
                                   intermediate_size=14336, max_seq_len=2048,
                                   rope_theta=500000.0, dtype=dtypes.bfloat16),
}


def init_params(cfg: LlamaConfig, seed: int = 0, scale_layers: int | None = None):
    """Initialize a params pytree with jax (host-side; not traced)."""
    import jax
    import jax.numpy as jnp

    n_layers = scale_layers if scale_layers is not None else cfg.n_layers
    key = jax.random.PRNGKey(seed)
    jd = cfg.dtype.jax

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (1.0 / math.sqrt(fan_in))).astype(jd)

    keys = iter(jax.random.split(key, 4 + n_layers * 7))
    params = {
        "tok_embedding": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "norm_f": jnp.ones((cfg.dim,), jd),
        "lm_head": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": [],
    }
    kv_dim = cfg.kv_heads * cfg.head_dim
    for _ in range(n_layers):
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.dim,), jd),
            "wq": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
            "wk": dense(next(keys), (kv_dim, cfg.dim), cfg.dim),
            "wv": dense(next(keys), (kv_dim, cfg.dim), cfg.dim),
            "wo": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
            "mlp_norm": jnp.ones((cfg.dim,), jd),
            "w_gate": dense(next(keys), (cfg.intermediate_size, cfg.dim), cfg.dim),
            "w_up": dense(next(keys), (cfg.intermediate_size, cfg.dim), cfg.dim),
            "w_down": dense(next(keys), (cfg.dim, cfg.intermediate_size), cfg.intermediate_size),
        })
        # wq..w_down consumed 5 keys; gate/up/down 3 more handled above
    return params


def _rope_tables(cfg: LlamaConfig, pos, dtype):
    """cos/sin for an arbitrary POSITION TENSOR: ``pos`` (any shape, any
    numeric dtype) -> tables of shape ``pos.shape + (hd/2,)``. The ONE
    owner of the rope frequency math — `_rope_cos_sin` (contiguous ranges)
    and the serving runner's per-request decode positions both build on it,
    so a future rope change (scaling, theta handling) cannot diverge
    between training, prefill, and paged decode."""
    hd = cfg.head_dim
    posf = ops.convert_element_type(pos, dtypes.float32)
    idx = ops.convert_element_type(ops.arange(hd // 2), dtypes.float32)  # (hd/2,)
    inv_freq = ops.pow(cfg.rope_theta, ops.true_divide(ops.mul(idx, -2.0), float(hd)))
    angles = ops.mul(ops.unsqueeze(posf, -1), inv_freq)  # pos.shape + (hd/2,)
    cos = ops.convert_element_type(ops.cos(angles), dtype)
    sin = ops.convert_element_type(ops.sin(angles), dtype)
    return cos, sin


def _rope_cos_sin(cfg: LlamaConfig, T: int, dtype, pos_offset=None):
    """cos/sin tables built from iota (fully fusible, no host constants).
    ``pos_offset`` shifts positions (context parallelism: local chunk start)."""
    pos = ops.convert_element_type(ops.arange(T), dtypes.float32)  # (T,)
    if pos_offset is not None:
        pos = ops.add(pos, ops.convert_element_type(pos_offset, dtypes.float32))
    return _rope_tables(cfg, pos, dtype)


def _apply_rope(x, cos, sin):
    """x: (B, H, T, hd); GPT-NeoX half-rotation."""
    hd = x.shape[-1]
    x1 = x[..., : hd // 2]
    x2 = x[..., hd // 2:]
    # cos/sin: (T, hd/2) -> broadcast over (B, H)
    rx1 = ops.sub(ops.mul(x1, cos), ops.mul(x2, sin))
    rx2 = ops.add(ops.mul(x2, cos), ops.mul(x1, sin))
    return ops.cat([rx1, rx2], -1)


def _project_qkv(x, layer, cfg: LlamaConfig, cos, sin):
    """RoPE'd q/k/v heads from a normed hidden state: q (B, n_heads, T, hd);
    k, v keep kv_heads (GQA expansion is the attention path's business)."""
    B, T = x.shape[0], x.shape[1]
    hd = cfg.head_dim
    q = ops.transpose(ops.reshape(ops.linear(x, layer["wq"]),
                                  (B, T, cfg.n_heads, hd)), (0, 2, 1, 3))
    k = ops.transpose(ops.reshape(ops.linear(x, layer["wk"]),
                                  (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
    v = ops.transpose(ops.reshape(ops.linear(x, layer["wv"]),
                                  (B, T, cfg.kv_heads, hd)), (0, 2, 1, 3))
    return _apply_rope(q, cos, sin), _apply_rope(k, cos, sin), v


def _mlp(h, layer, cfg: LlamaConfig):
    """Residual SwiGLU MLP sub-block."""
    x = ops.rms_norm(h, layer["mlp_norm"], eps=cfg.norm_eps)
    gate = ops.silu(ops.linear(x, layer["w_gate"]))
    up = ops.linear(x, layer["w_up"])
    return ops.add(h, ops.linear(ops.mul(gate, up), layer["w_down"]))


def _block(h, layer, cfg: LlamaConfig, cos, sin):
    """One decoder layer: RMSNorm → GQA attention → RMSNorm → SwiGLU MLP."""
    B, T = h.shape[0], h.shape[1]
    n_rep = cfg.n_heads // cfg.kv_heads
    hd = cfg.head_dim

    x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
    q, k, v = _project_qkv(x, layer, cfg, cos, sin)
    if n_rep > 1:  # GQA: repeat kv heads
        k = ops.reshape(ops.expand(ops.unsqueeze(k, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                        (B, cfg.n_heads, T, hd))
        v = ops.reshape(ops.expand(ops.unsqueeze(v, 2), (B, cfg.kv_heads, n_rep, T, hd)),
                        (B, cfg.n_heads, T, hd))
    attn = ops.scaled_dot_product_attention(q, k, v, is_causal=True)
    # width is n_heads*hd (== dim/tp_size under tensor parallelism)
    attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, cfg.n_heads * hd))
    h = ops.add(h, ops.linear(attn, layer["wo"]))
    return _mlp(h, layer, cfg)


def forward_hidden(params, tokens, cfg: LlamaConfig, remat: bool = False):
    """tokens: (B, T) int32 -> final hidden states (B, T, D) (pre-lm_head).

    ``remat=True`` wraps every transformer block in ``tt.checkpoint``:
    the backward recomputes each block from its input instead of saving
    intermediates — per-layer activation memory drops from ~dozens of
    (B,T,*) tensors to one, which is what lets deep 7B-geometry stacks
    train on a single 16 GB chip (reference analog: litgpt
    benchmark's activation checkpointing flag)."""
    B, T = tokens.shape
    h = ops.embedding(tokens, params["tok_embedding"])  # (B, T, D)
    from thunder_tpu.distributed import current_cp

    cp = current_cp()
    pos_offset = None
    if cp is not None:  # sequence sharded: positions start at my_chunk * T_local
        from thunder_tpu.distributed import prims as dist_prims

        pos_offset = ops.mul(dist_prims.axis_index(cp[0]), T)
    cos, sin = _rope_cos_sin(cfg, T, h.dtype, pos_offset)
    n_rep = cfg.n_heads // cfg.kv_heads
    hd = cfg.head_dim

    if remat:
        from thunder_tpu.core.rematerialization import checkpoint as _ckpt

        block = _ckpt(lambda x, lyr: _block(x, lyr, cfg, cos, sin))
        for layer in params["layers"]:
            h = block(h, layer)
    else:
        for layer in params["layers"]:
            h = _block(h, layer, cfg, cos, sin)

    return ops.rms_norm(h, params["norm_f"], eps=cfg.norm_eps)


def forward(params, tokens, cfg: LlamaConfig, remat: bool = False):
    """tokens: (B, T) int32 -> logits (B, T, vocab)."""
    return ops.linear(forward_hidden(params, tokens, cfg, remat=remat),
                      params["lm_head"])


def loss_fn(params, tokens, targets, cfg: LlamaConfig, remat: bool = False):
    logits = forward(params, tokens, cfg, remat=remat)
    B, T, V = logits.shape
    logits = ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32)
    return ops.cross_entropy(logits, ops.reshape(targets, (B * T,)))


def fused_loss_fn(params, tokens, targets, cfg: LlamaConfig, chunk: int = 8192,
                  remat: bool = False):
    """Chunked-vocab loss: lm_head projection fused into the cross-entropy
    (``nn.fused_linear_cross_entropy``) — the (B*T, vocab) logits are never
    materialized. Drop-in for ``loss_fn`` when activation memory is the
    constraint (large vocab / long sequence)."""
    from thunder_tpu.ops import nn as tnn

    h = forward_hidden(params, tokens, cfg, remat=remat)
    B, T, D = h.shape
    loss, _lse = tnn.fused_linear_cross_entropy(
        ops.reshape(h, (B * T, D)), params["lm_head"],
        ops.reshape(targets, (B * T,)), chunk=chunk)
    return loss


def stack_layers(params):
    """Convert the per-layer list-of-dicts into stacked arrays with a leading
    layer dim — the layout pipeline parallelism shards across the ``pp``
    axis (each device receives a contiguous layer chunk)."""
    import jax.numpy as jnp

    stacked = dict(params)
    layers = params["layers"]
    stacked["layers"] = {k: jnp.stack([l[k] for l in layers]) for k in layers[0]}
    return stacked


def pipeline_fns(cfg: LlamaConfig):
    """(embed_fn, stage_fn, head_loss_fn) for
    ``thunder_tpu.distributed.make_pipeline_loss``. ``stage_fn`` reads its
    layer-chunk length from the local (sharded) stacked shape, so the same
    trace works for any pp degree."""

    def embed_fn(params, tokens):
        return ops.embedding(tokens, params["tok_embedding"])

    def stage_fn(params, h):
        T = h.shape[1]
        cos, sin = _rope_cos_sin(cfg, T, h.dtype)
        n_local = params["layers"]["attn_norm"].shape[0]
        for i in range(n_local):
            layer = {k: v[i] for k, v in params["layers"].items()}
            h = _block(h, layer, cfg, cos, sin)
        return h

    def head_loss_fn(params, h, targets):
        h = ops.rms_norm(h, params["norm_f"], eps=cfg.norm_eps)
        logits = ops.linear(h, params["lm_head"])
        B, T, V = logits.shape
        logits = ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32)
        return ops.cross_entropy(logits, ops.reshape(targets, (B * T,)))

    return embed_fn, stage_fn, head_loss_fn


PP_STAGE_PATTERNS = (r"\['layers'\]",)


def tp_config(cfg: LlamaConfig, tp_size: int) -> LlamaConfig:
    """Local (per-shard) config for Megatron-style tensor parallelism:
    heads and MLP width divided across the tp axis (reference
    ``thunder/distributed/tensor_parallel/``: the consumer-rewrite visitor;
    here the model is shape-polymorphic so a local config suffices)."""
    import dataclasses

    check_ok = (cfg.n_heads % tp_size == 0 and cfg.kv_heads % tp_size == 0
                and cfg.intermediate_size % tp_size == 0)
    if not check_ok:
        raise ValueError(f"config {cfg.name} not divisible by tp={tp_size}")
    return dataclasses.replace(
        cfg,
        n_heads=cfg.n_heads // tp_size,
        n_kv_heads=cfg.kv_heads // tp_size,
        intermediate_size=cfg.intermediate_size // tp_size,
        head_dim_override=cfg.head_dim,
    )


TP_COLUMN_PATTERNS = (r"\['wq'\]", r"\['wk'\]", r"\['wv'\]", r"\['w_gate'\]", r"\['w_up'\]")
TP_ROW_PATTERNS = (r"\['wo'\]", r"\['w_down'\]")


def num_params(cfg: LlamaConfig, n_layers: int | None = None) -> int:
    n_layers = n_layers if n_layers is not None else cfg.n_layers
    kv_dim = cfg.kv_heads * cfg.head_dim
    per_layer = (2 * cfg.dim  # norms
                 + 2 * cfg.dim * cfg.dim  # wq, wo
                 + 2 * kv_dim * cfg.dim  # wk, wv
                 + 3 * cfg.dim * cfg.intermediate_size)  # gate/up/down
    return (2 * cfg.vocab_size * cfg.dim + cfg.dim + n_layers * per_layer)


def flops_per_token(cfg: LlamaConfig, seq_len: int, n_layers: int | None = None) -> float:
    """Model FLOPs per token for fwd+bwd (6N + attention terms)."""
    n = num_params(cfg, n_layers) - 2 * cfg.vocab_size * cfg.dim
    attn = 2 * 2 * (n_layers or cfg.n_layers) * cfg.dim * seq_len  # qk^T + pv per token
    return 6 * (n + cfg.vocab_size * cfg.dim) + 3 * attn


# ---------------------------------------------------------------------------
# KV-cache inference (autoregressive decoding)
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int | None = None,
                  n_layers: int | None = None):
    """Per-layer K/V buffers (B, kv_heads, max_len, head_dim)."""
    import jax.numpy as jnp

    max_len = max_len or cfg.max_seq_len
    n = n_layers if n_layers is not None else cfg.n_layers
    shape = (batch, cfg.kv_heads, max_len, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype.jax), "v": jnp.zeros(shape, cfg.dtype.jax)}
            for _ in range(n)]


def forward_step(params, tokens, cache, pos, cfg: LlamaConfig, last_idx=None):
    """Incremental forward: ``tokens`` (B, T) occupy positions
    [pos, pos+T) (prefill T>1 or decode T=1); ``pos`` is a traced scalar so
    one compiled program serves every decode step. Returns
    (logits (B, T, vocab), updated cache) — or (B, 1, vocab) when
    ``last_idx`` selects a single output row before the lm_head."""
    B, T = tokens.shape
    hd = cfg.head_dim
    n_rep = cfg.n_heads // cfg.kv_heads
    max_len = cache[0]["k"].shape[2]
    h = ops.embedding(tokens, params["tok_embedding"])
    cos, sin = _rope_cos_sin(cfg, T, h.dtype, pos_offset=pos)
    zero = ops.full((), 0, dtype=dtypes.int32)
    new_cache = []
    # validity of cache column j for local row i: j <= pos + i
    col = ops.arange(max_len)                                   # (max_len,)
    row = ops.add(ops.arange(T), pos)                           # (T,)
    valid = ops.le(ops.unsqueeze(col, 0), ops.unsqueeze(row, 1))  # (T, max_len)

    for layer, c in zip(params["layers"], cache):
        x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
        q, k, v = _project_qkv(x, layer, cfg, cos, sin)
        ck = prims.dynamic_update_slice(c["k"], k, (zero, zero, pos, zero))
        cv = prims.dynamic_update_slice(c["v"], v, (zero, zero, pos, zero))
        new_cache.append({"k": ck, "v": cv})
        # grouped-query attention WITHOUT materializing the expanded cache:
        # fold the group dim into q's row dim — q (B, H, T, hd) becomes
        # (B, kv_heads, n_rep*T, hd) and matmuls run against the unexpanded
        # (B, kv_heads, max_len, hd) cache
        qg = ops.reshape(q, (B, cfg.kv_heads, n_rep * T, hd))
        qf = ops.convert_element_type(qg, dtypes.float32)
        kf = ops.convert_element_type(ck, dtypes.float32)
        scores = ops.mul(ops.matmul(qf, kf.mT), 1.0 / math.sqrt(hd))
        scores = ops.reshape(scores, (B, cfg.n_heads, T, max_len))
        neg = ops.full((), float("-inf"), dtype=dtypes.float32)
        scores = ops.where(valid, scores, neg)
        attn_w = ops.convert_element_type(ops.softmax(scores, -1), h.dtype)
        attn = ops.matmul(ops.reshape(attn_w, (B, cfg.kv_heads, n_rep * T, max_len)), cv)
        attn = ops.reshape(attn, (B, cfg.n_heads, T, hd))
        attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, cfg.n_heads * hd))
        h = ops.add(h, ops.linear(attn, layer["wo"]))
        h = _mlp(h, layer, cfg)

    h = ops.rms_norm(h, params["norm_f"], eps=cfg.norm_eps)
    if last_idx is not None:
        # logits only at row ``last_idx`` (traced 0-d index): the lm_head
        # projection runs on (B, 1, dim), not (B, T, dim) — for a Tp=512
        # prefill that is 512x less lm_head work and no (B, T, vocab)
        # materialization (measured r4: the whole prefill gap to the
        # hand-written baseline was this projection)
        zero = ops.full((), 0, dtype=dtypes.int32)
        h = prims.dynamic_slice(h, (zero, last_idx, zero), (B, 1, cfg.dim))
    return ops.linear(h, params["lm_head"]), new_cache


# shared decode/prefill step cache: tt.jit functions cache per input shape
# internally, so one entry per (config, n_layers) bounds compilations across
# generate() calls — a bucketed prefill (prefill_buckets) then compiles at
# most len(buckets) prefill programs total
_step_fns: dict = {}


def _get_step_fns(cfg: LlamaConfig, n_layers):
    import thunder_tpu as tt

    key = (repr(cfg), n_layers)
    if key in _step_fns:
        return _step_fns[key]

    def _step(p, t, c, pos):
        T = t.shape[1]
        last = ops.full((), T - 1, dtype=dtypes.int32)
        logits, nc = forward_step(p, t, c, pos, cfg, last_idx=last)
        return ops.squeeze(logits, 1), nc

    def _prefill(p, t, c, pos, true_len):
        # padded prefill: logits at the LAST REAL position (true_len - 1),
        # a traced 0-d index sliced BEFORE the lm_head — the compiled
        # program is shared by every prompt length in the bucket and never
        # materializes (B, T, vocab)
        logits, nc = forward_step(p, t, c, pos, cfg,
                                  last_idx=ops.sub(true_len, 1))
        return ops.squeeze(logits, 1), nc

    fns = (tt.jit(_step, donate_argnums=(2,)), tt.jit(_prefill, donate_argnums=(2,)))
    _step_fns[key] = fns
    return fns


def generate(params, cfg: LlamaConfig, prompt, max_new_tokens: int,
             temperature: float = 0.0, key=None, max_len: int | None = None,
             n_layers: int | None = None, prefill_buckets=None):
    """Autoregressive decoding with a KV cache: prefill once, then one
    compiled decode step reused for every position (``pos`` is a traced
    array — no per-step recompilation). Greedy when ``temperature == 0``,
    else softmax sampling via Gumbel trick with the keyed functional RNG.

    ``prefill_buckets=(128, 512, ...)``: pad the prompt to a bucket ladder so
    ragged prompt lengths compile at most ``len(buckets)`` prefill programs
    (step functions are shared across ``generate`` calls per config). The
    pad positions write garbage K/V beyond ``Tp`` — harmless: the causal
    mask hides cols > row, and decode overwrites each position before it is
    first attended."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import thunder_tpu as tt

    if max_new_tokens <= 0:
        return jnp.zeros((len(prompt), 0), jnp.int32)
    prompt = jnp.asarray(prompt)
    B, Tp = prompt.shape
    prompt_in, Tpad = prompt, Tp
    if prefill_buckets is not None:
        from thunder_tpu.data import LengthBucketer

        bk = LengthBucketer(prefill_buckets)
        Tpad = bk.bucket_for(Tp)
        if Tpad != Tp:
            prompt_in = jnp.pad(prompt, ((0, 0), (0, Tpad - Tp)))
        if max_len is None:
            # bucket the KV-cache length too: the decode step's compiled
            # shape is (B, 1) tokens × (B, H, max_len, hd) cache, so an
            # un-bucketed max_len would recompile decode per prompt length
            align = bk.buckets[0]
            max_len = min(cfg.max_seq_len,
                          max(Tpad, -(-(Tp + max_new_tokens) // align) * align))
    max_len = max_len or max(Tp + max_new_tokens, Tpad)
    if Tpad > max_len:
        raise ValueError(
            f"prefill bucket {Tpad} (for prompt length {Tp}) exceeds the KV "
            f"cache length (max_len={max_len}); use a tighter bucket ladder "
            f"or a larger max_len")
    if Tp + max_new_tokens > max_len or max_len > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({Tp}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"context window (max_len={max_len}, cfg.max_seq_len={cfg.max_seq_len})")
    cache = init_kv_cache(cfg, B, max_len, n_layers=n_layers)

    # the step returns only the LAST position's logits (prefill would
    # otherwise run lm_head over the whole prompt and ship (B, Tp, vocab)
    # to the host); the cache is donated so XLA updates it in place instead
    # of copying ~all of it every token
    step_fn, prefill_fn = _get_step_fns(cfg, n_layers)

    def pick(logits_last, key):
        if temperature == 0.0:
            return jnp.argmax(logits_last, -1).astype(jnp.int32)
        g = -jnp.log(-jnp.log(jax.random.uniform(key, logits_last.shape) + 1e-10) + 1e-10)
        return jnp.argmax(logits_last / temperature + g, -1).astype(jnp.int32)

    if prefill_buckets is not None:
        last, cache = prefill_fn(params, prompt_in, cache, jnp.int32(0), jnp.int32(Tp))
    else:
        last, cache = step_fn(params, prompt, cache, jnp.int32(0))
    if key is None:
        key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    tok = pick(last, sub)
    out = [tok]
    for i in range(1, max_new_tokens):
        last, cache = step_fn(params, tok[:, None], cache, jnp.int32(Tp + i - 1))
        key, sub = jax.random.split(key)
        tok = pick(last, sub)
        out.append(tok)
    return jnp.stack(out, axis=1)  # (B, max_new_tokens)


def generate_fused(params, cfg: LlamaConfig, prompt, max_new_tokens: int,
                   max_len: int | None = None, n_layers: int | None = None):
    """Greedy decoding with the WHOLE decode loop compiled as one XLA
    program: ``lax.scan`` over the framework-traced step, so generation is
    a single device dispatch — no per-token host round-trips (on a
    tunneled/remote chip the per-step ``generate`` loop pays one RTT per
    token; this pays one total). The scanned body IS the compiled entry's
    computation (same trace, same executors) — not a reimplementation.
    Reference analog: litgpt's generate is a per-step Python loop; this is
    the TPU-native replacement."""
    import jax
    import jax.numpy as jnp

    from thunder_tpu.core.pytree import tree_flatten

    prompt = jnp.asarray(prompt)
    B, Tp = prompt.shape
    max_len = max_len or (Tp + max_new_tokens)
    check(Tp + max_new_tokens <= max_len <= cfg.max_seq_len,
          lambda: f"prompt ({Tp}) + max_new_tokens ({max_new_tokens}) "
                  f"exceeds max_len={max_len} / cfg.max_seq_len={cfg.max_seq_len}")
    cache = init_kv_cache(cfg, B, max_len, n_layers=n_layers)
    step_fn, _ = _get_step_fns(cfg, n_layers)

    last, cache = step_fn(params, prompt, cache, jnp.int32(0))
    tok = jnp.argmax(last, -1).astype(jnp.int32)[:, None]
    if max_new_tokens == 1:
        return tok

    # the compiled decode entry for (B, 1) tokens; its computation_fn is the
    # pure-jax callable the scan body invokes
    entry = step_fn.compile(params, tok, cache, jnp.int32(Tp))
    comp = entry.computation_fn
    t_idx = entry.tensor_indices

    def body(carry, _):
        tok, cache, pos = carry
        flat, _ = tree_flatten(((params, tok, cache, pos), {}))
        lastl, nc = comp(*[flat[i] for i in t_idx])
        ntok = jnp.argmax(lastl, -1).astype(jnp.int32)[:, None]
        return (ntok, nc, pos + 1), ntok[:, 0]

    @jax.jit
    def decode_all(tok, cache):
        (_, _, _), toks = jax.lax.scan(
            body, (tok, cache, jnp.int32(Tp)), None,
            length=max_new_tokens - 1)
        return jnp.swapaxes(toks, 0, 1)  # (B, n-1)

    rest = decode_all(tok, cache)
    return jnp.concatenate([tok, rest], axis=1)

"""Encoder-decoder transformer (BART/T5-style) with cross-attention.

Reference parity: the reference's zoo includes a BART self-attention test
module (``thunder/tests/hf_bart_self_attn.py``); here the full seq2seq
architecture is provided — bidirectional encoder, causal decoder with
cross-attention over encoder states, learned positions, tied lm_head —
exercising the one attention pattern (cross-attention, T != S) the
decoder-only families never hit.
"""

from __future__ import annotations

from dataclasses import dataclass

from thunder_tpu import ops
from thunder_tpu.core import dtypes


@dataclass(frozen=True)
class Seq2SeqConfig:
    name: str = "tiny"
    vocab_size: int = 512
    dim: int = 64
    n_heads: int = 4
    enc_layers: int = 2
    dec_layers: int = 2
    ffn_dim: int = 256
    max_seq_len: int = 128
    norm_eps: float = 1e-5
    dtype: dtypes.dtype = dtypes.float32

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS = {
    "tiny": Seq2SeqConfig(),
    "bart-base": Seq2SeqConfig(name="bart-base", vocab_size=50265, dim=768, n_heads=12,
                               enc_layers=6, dec_layers=6, ffn_dim=3072, max_seq_len=1024),
}


def init_params(cfg: Seq2SeqConfig, seed: int = 0):
    import jax
    import numpy as np

    jd = cfg.dtype.jax
    key = jax.random.PRNGKey(seed)
    ks = iter(jax.random.split(key, 8 + 12 * (cfg.enc_layers + cfg.dec_layers)))
    D, F = cfg.dim, cfg.ffn_dim

    def _dense(shape, std=0.02):
        return (jax.random.normal(next(ks), shape) * std).astype(jd)

    def attn_block():
        return {"wq": _dense((D, D)), "wk": _dense((D, D)),
                "wv": _dense((D, D)), "wo": _dense((D, D))}

    def ffn_block():
        return {"w1": _dense((F, D)), "w2": _dense((D, F))}

    ones = lambda: np.ones((D,), dtype=cfg.dtype.jax)
    params = {
        "tok_embedding": _dense((cfg.vocab_size, D)),
        "pos_embedding": _dense((cfg.max_seq_len, D)),
        "enc": [{"attn": attn_block(), "attn_norm": ones(),
                 "ffn": ffn_block(), "ffn_norm": ones()} for _ in range(cfg.enc_layers)],
        "dec": [{"self_attn": attn_block(), "self_norm": ones(),
                 "cross_attn": attn_block(), "cross_norm": ones(),
                 "ffn": ffn_block(), "ffn_norm": ones()} for _ in range(cfg.dec_layers)],
        "final_norm": ones(),
    }
    return params


def _attend(x, kv, blk, cfg: Seq2SeqConfig, *, causal: bool):
    """Multi-head attention; ``kv`` may differ from ``x`` (cross-attention)."""
    B, T = x.shape[0], x.shape[1]
    S = kv.shape[1]
    H, hd = cfg.n_heads, cfg.head_dim
    q = ops.transpose(ops.reshape(ops.linear(x, blk["wq"]), (B, T, H, hd)), (0, 2, 1, 3))
    k = ops.transpose(ops.reshape(ops.linear(kv, blk["wk"]), (B, S, H, hd)), (0, 2, 1, 3))
    v = ops.transpose(ops.reshape(ops.linear(kv, blk["wv"]), (B, S, H, hd)), (0, 2, 1, 3))
    o = ops.scaled_dot_product_attention(q, k, v, is_causal=causal)
    return ops.linear(ops.reshape(ops.transpose(o, (0, 2, 1, 3)), (B, T, cfg.dim)), blk["wo"])


def _ffn(x, blk):
    return ops.linear(ops.gelu(ops.linear(x, blk["w1"])), blk["w2"])


def _embed(params, tokens, cfg: Seq2SeqConfig):
    T = tokens.shape[1]
    if T > cfg.max_seq_len:
        raise ValueError(f"sequence length {T} exceeds max_seq_len {cfg.max_seq_len}")
    h = ops.embedding(tokens, params["tok_embedding"])
    pos = ops.narrow(params["pos_embedding"], 0, 0, T)
    return ops.add(h, ops.unsqueeze(pos, 0))


def encode(params, src_tokens, cfg: Seq2SeqConfig):
    """Bidirectional encoder: (B, S) int32 -> (B, S, D)."""
    h = _embed(params, src_tokens, cfg)
    for layer in params["enc"]:
        x = ops.rms_norm(h, layer["attn_norm"], eps=cfg.norm_eps)
        h = ops.add(h, _attend(x, x, layer["attn"], cfg, causal=False))
        x = ops.rms_norm(h, layer["ffn_norm"], eps=cfg.norm_eps)
        h = ops.add(h, _ffn(x, layer["ffn"]))
    return h


def decode(params, tgt_tokens, enc_out, cfg: Seq2SeqConfig):
    """Causal decoder with cross-attention: (B, T) + (B, S, D) -> logits."""
    h = _embed(params, tgt_tokens, cfg)
    for layer in params["dec"]:
        x = ops.rms_norm(h, layer["self_norm"], eps=cfg.norm_eps)
        h = ops.add(h, _attend(x, x, layer["self_attn"], cfg, causal=True))
        x = ops.rms_norm(h, layer["cross_norm"], eps=cfg.norm_eps)
        h = ops.add(h, _attend(x, enc_out, layer["cross_attn"], cfg, causal=False))
        x = ops.rms_norm(h, layer["ffn_norm"], eps=cfg.norm_eps)
        h = ops.add(h, _ffn(x, layer["ffn"]))
    h = ops.rms_norm(h, params["final_norm"], eps=cfg.norm_eps)
    # tied lm_head: project onto the token embedding
    return ops.linear(h, params["tok_embedding"])


def forward(params, src_tokens, tgt_tokens, cfg: Seq2SeqConfig):
    return decode(params, tgt_tokens, encode(params, src_tokens, cfg), cfg)


def loss_fn(params, src_tokens, tgt_tokens, labels, cfg: Seq2SeqConfig):
    logits = forward(params, src_tokens, tgt_tokens, cfg)
    B, T, V = logits.shape
    logits = ops.convert_element_type(ops.reshape(logits, (B * T, V)), dtypes.float32)
    return ops.cross_entropy(logits, ops.reshape(labels, (B * T,)))

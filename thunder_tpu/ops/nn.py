"""NN composite operations.

Each composite is a Symbol with a stable ``nn.*`` id and a prim
decomposition, so operator executors can claim it whole — the Pallas
flash-attention executor claims ``nn.scaled_dot_product_attention`` exactly
like the reference's cudnnex/sdpaex claim torch SDPA
(``thunder/executors/sdpaex.py:239``, ``cudnnex.py:425``), and the fused
cross-entropy kernel claims ``nn.cross_entropy`` (apex/triton analog).
"""

from __future__ import annotations

import math

from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.baseutils import check, canonicalize_dim
from thunder_tpu.core.proxies import TensorProxy, pyval
import thunder_tpu.ops as ops
from thunder_tpu.ops import _tensor_like, opsymbol


@opsymbol(id="nn.embedding")
def embedding(ids, weight, padding_idx=None):
    check(weight.ndim == 2, lambda: (
        f"embedding: weight must be (num_embeddings, dim), got "
        f"{weight.ndim}-D {tuple(weight.shape)}"))
    out = prims.take(weight, ids, 0)
    return out


@opsymbol(id="nn.one_hot")
def one_hot(ids, num_classes: int):
    check(int(num_classes) > 0,
          lambda: f"one_hot: num_classes must be positive, got {num_classes}")
    classes = prims.iota(num_classes, dtype=dtypes.int32, device=ids.device)
    classes = ops.expand_to(classes, ids.shape + (num_classes,))
    expanded = ops.expand_to(ops.unsqueeze(ids, -1), ids.shape + (num_classes,))
    return ops.convert_element_type(ops.eq(expanded, classes), dtypes.int32)


@opsymbol(id="nn.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    _tensor_like(a, "layer_norm")
    nd = len(normalized_shape)
    check(tuple(a.shape[-nd:]) == tuple(normalized_shape),
          lambda: f"layer_norm: normalized_shape {normalized_shape} != trailing dims of {a.shape}")
    dims = tuple(range(a.ndim - nd, a.ndim))
    x = ops.convert_element_type(a, dtypes.float32) if a.dtype in (dtypes.float16, dtypes.bfloat16) else a
    m = ops.mean(x, dims, keepdim=True)
    centered = ops.sub(x, m)
    v = ops.mean(ops.mul(centered, centered), dims, keepdim=True)
    out = ops.mul(centered, ops.rsqrt(ops.add(v, eps)))
    if weight is not None:
        out = ops.mul(out, weight)
    if bias is not None:
        out = ops.add(out, bias)
    return ops.convert_element_type(out, a.dtype)


@opsymbol(id="nn.rms_norm")
def rms_norm(a, weight=None, eps: float = 1e-5, dim: int = -1):
    d = canonicalize_dim(a.ndim, dim)
    x = ops.convert_element_type(a, dtypes.float32) if a.dtype in (dtypes.float16, dtypes.bfloat16) else a
    ms = ops.mean(ops.mul(x, x), d, keepdim=True)
    out = ops.mul(x, ops.rsqrt(ops.add(ms, eps)))
    out = ops.convert_element_type(out, a.dtype)
    if weight is not None:
        out = ops.mul(out, weight)
    return out


@opsymbol(id="nn.rms_norm_residual")
def rms_norm_residual(residual, a, weight=None, eps: float = 1e-5):
    """Fused residual-add + RMS norm: ``h = residual + a`` followed by
    ``rms_norm(h, weight)``; returns ``(h, normed)``.

    Both values escape in a transformer block — ``h`` is the residual
    stream, ``normed`` feeds the next projection — so the epilogue fusion
    pass rewrites ``add → rms_norm`` chains into this composite (which the
    Pallas executor claims as one kernel, saving an HBM round-trip of the
    residual stream per block). Unclaimed, this decomposition is exactly the
    unfused ops, so numerics are identical either way.
    """
    _tensor_like(a, "rms_norm_residual")
    check(tuple(residual.shape) == tuple(a.shape),
          lambda: f"rms_norm_residual: residual shape {tuple(residual.shape)} "
                  f"!= input shape {tuple(a.shape)}")
    h = ops.add(residual, a)
    return h, rms_norm(h, weight, eps=eps)


_LINEAR_ACT_FNS = {
    "relu": lambda y: ops.relu(y),
    "silu": lambda y: ops.silu(y),
    "gelu": lambda y: ops.gelu(y),
    "gelu_tanh": lambda y: ops.gelu(y, approximate="tanh"),
}


@opsymbol(id="nn.linear_act")
def linear_act(a, w, bias=None, act: str = "relu"):
    """Fused ``act(a @ w.T + bias)`` — the GEMM-epilogue composite the
    pattern pass builds from ``nn.linear → activation`` chains, claimable by
    the Pallas executor as a single kernel (activation applied to the f32
    accumulator tile while it is still in VMEM). ``act`` is one of
    ``relu | silu | gelu | gelu_tanh``."""
    check(act in _LINEAR_ACT_FNS,
          lambda: f"linear_act: unknown activation {act!r}; known: {sorted(_LINEAR_ACT_FNS)}")
    return _LINEAR_ACT_FNS[act](ops.linear(a, w, bias))


_SUBBLOCK_ACTS = ("silu", "relu", "gelu", "gelu_tanh")


@opsymbol(id="nn.mlp_subblock")
def mlp_subblock(residual, x, w_norm, w_gate, w_up, w_down, *,
                 act: str = "silu", eps: float = 1e-5):
    """Whole transformer MLP sub-block as ONE claimable composite — the
    block planner's megakernel unit (``core/fusion_passes.block_fusion_pass``)::

        h   = residual + x          # attention-out residual add
        n   = rms_norm(h, w_norm)
        y   = act(n @ w_gate.T) * (n @ w_up.T)
        out = h + y @ w_down.T      # second residual add

    The decomposition below is exactly the unfused chain (that is the
    numerics contract when nothing claims it, and the per-op XLA fallback
    the quarantine/bisection machinery recompiles to); the Pallas executor
    claims it as a single streamed-weight kernel that keeps every interior
    value (n, the gate/up pre-activations, the SwiGLU product) in VMEM.
    The VJP rule below keeps it claimable under autodiff: only the INPUTS
    are saved and the backward recomputes the interiors, flash-style, via
    the equally-claimable ``nn.mlp_subblock_bwd``.
    """
    _tensor_like(x, "mlp_subblock")
    check(tuple(residual.shape) == tuple(x.shape) and residual.dtype == x.dtype,
          lambda: f"mlp_subblock: residual {tuple(residual.shape)}/{residual.dtype} "
                  f"does not match x {tuple(x.shape)}/{x.dtype}")
    check(act in _SUBBLOCK_ACTS,
          lambda: f"mlp_subblock: unknown activation {act!r}; known: {_SUBBLOCK_ACTS}")
    h = ops.add(residual, x)
    n = rms_norm(h, w_norm, eps=eps)
    gate = _LINEAR_ACT_FNS[act](ops.linear(n, w_gate))
    up = ops.linear(n, w_up)
    return ops.add(h, ops.linear(ops.mul(gate, up), w_down))


@opsymbol(id="nn.mlp_subblock_bwd")
def mlp_subblock_bwd(g, residual, x, w_norm, w_gate, w_up, w_down, *,
                     act: str = "silu", eps: float = 1e-5):
    """Backward of :func:`mlp_subblock` from the saved INPUTS only:
    recomputes the forward interiors (the flash-attention memory contract
    applied to the MLP sub-block) and returns
    ``(dh, dw_norm, dw_gate, dw_up, dw_down)`` where ``dh`` is the
    cotangent of BOTH ``residual`` and ``x`` (they are summands of the
    same ``h``). Claimable by the Pallas executor as the backward
    megakernel pair; unclaimed, this decomposition is the exact chain
    rule over the unfused ops."""
    check(act in _SUBBLOCK_ACTS,
          lambda: f"mlp_subblock_bwd: unknown activation {act!r}")
    dt = x.dtype
    wide = dtypes.float32 if dt in (dtypes.float16, dtypes.bfloat16) else dt
    h = ops.add(residual, x)
    h32 = ops.convert_element_type(h, wide)
    ms = ops.mean(ops.mul(h32, h32), -1, keepdim=True)
    r = ops.rsqrt(ops.add(ms, eps))
    xhat = ops.mul(h32, r)                      # pre-weight normalized rows
    n = ops.mul(ops.convert_element_type(xhat, dt), w_norm)
    gpre = ops.linear(n, w_gate)
    ga = _LINEAR_ACT_FNS[act](gpre)
    up = ops.linear(n, w_up)
    y = ops.mul(ga, up)

    g32 = ops.convert_element_type(g, wide)
    # out = h + y @ w_down.T
    dy = ops.convert_element_type(
        prims.dot_general(g, w_down, contract_dims=((g.ndim - 1,), (0,))), dt)
    N = 1
    for d in g.shape[:-1]:
        N *= int(d)
    g2 = ops.reshape(g, (N, g.shape[-1]))
    y2 = ops.reshape(y, (N, y.shape[-1]))
    dw_down = ops.convert_element_type(
        prims.dot_general(g2, y2, contract_dims=((0,), (0,)),
                          preferred_element_type=wide), w_down.dtype)
    dga = ops.mul(dy, up)
    dup = ops.mul(dy, ga)
    dgpre = ops.mul(dga, _act_grad(act, gpre))
    # dn = dgpre @ w_gate + dup @ w_up
    dn = ops.add(
        prims.dot_general(dgpre, w_gate, contract_dims=((dgpre.ndim - 1,), (0,))),
        prims.dot_general(dup, w_up, contract_dims=((dup.ndim - 1,), (0,))))
    dgpre2 = ops.reshape(dgpre, (N, dgpre.shape[-1]))
    dup2 = ops.reshape(dup, (N, dup.shape[-1]))
    n2 = ops.reshape(n, (N, n.shape[-1]))
    dw_gate = ops.convert_element_type(
        prims.dot_general(dgpre2, n2, contract_dims=((0,), (0,)),
                          preferred_element_type=wide), w_gate.dtype)
    dw_up = ops.convert_element_type(
        prims.dot_general(dup2, n2, contract_dims=((0,), (0,)),
                          preferred_element_type=wide), w_up.dtype)
    # rms_norm backward (same math as the nn.rms_norm VJP rule)
    dn32 = ops.convert_element_type(dn, wide)
    dw_norm = None
    if w_norm is not None and isinstance(w_norm, TensorProxy):
        lead = tuple(range(x.ndim - 1))
        dwn = ops.mul(dn32, xhat) if not lead else ops.sum(ops.mul(dn32, xhat), lead)
        dw_norm = ops.convert_element_type(dwn, w_norm.dtype)
        gxhat = ops.mul(dn32, ops.convert_element_type(w_norm, wide))
    else:
        gxhat = dn32
    proj = ops.mean(ops.mul(gxhat, xhat), -1, keepdim=True)
    dh_norm = ops.mul(r, ops.sub(gxhat, ops.mul(xhat, proj)))
    dh = ops.convert_element_type(ops.add(g32, dh_norm), dt)
    return dh, dw_norm, dw_gate, dw_up, dw_down


def _act_grad(act: str, a):
    """d act(a) / d a, in ``a``'s dtype (traced ops)."""
    if act == "relu":
        return ops.convert_element_type(ops.gt(a, 0.0), a.dtype)
    if act == "silu":
        sig = ops.sigmoid(a)
        return ops.mul(sig, ops.add(1.0, ops.mul(a, ops.sub(1.0, sig))))
    if act == "gelu":
        # Φ(a) + a·φ(a)
        phi_cdf = ops.mul(ops.add(ops.erf(ops.mul(a, 1.0 / math.sqrt(2.0))), 1.0), 0.5)
        pdf = ops.mul(ops.exp(ops.mul(ops.mul(a, a), -0.5)), 1.0 / math.sqrt(2.0 * math.pi))
        return ops.add(phi_cdf, ops.mul(a, pdf))
    check(act == "gelu_tanh", lambda: f"_act_grad: unknown activation {act!r}")
    c = math.sqrt(2.0 / math.pi)
    a2 = ops.mul(a, a)
    u = ops.mul(ops.add(a, ops.mul(ops.mul(a2, a), 0.044715)), c)
    t = ops.tanh(u)
    sech2 = ops.sub(1.0, ops.mul(t, t))
    du = ops.mul(ops.add(1.0, ops.mul(a2, 3.0 * 0.044715)), c)
    return ops.add(ops.mul(ops.add(t, 1.0), 0.5),
                   ops.mul(ops.mul(ops.mul(a, sech2), du), 0.5))


@opsymbol(id="nn.dropout")
def dropout(a, p: float = 0.5, training: bool = True):
    p = float(pyval(p))
    if not training or p == 0.0:
        return a
    check(0.0 <= p < 1.0, lambda: f"dropout p={p} out of range")
    keep = ops.bernoulli(1.0 - p, a.shape, dtype=a.dtype)
    return ops.mul(ops.mul(a, keep), 1.0 / (1.0 - p))


@opsymbol(id="nn.mse_loss")
def mse_loss(input, target, reduction: str = "mean"):
    d = ops.sub(input, target)
    sq = ops.mul(d, d)
    if reduction == "mean":
        return ops.mean(sq)
    if reduction == "sum":
        return ops.sum(sq)
    return sq


@opsymbol(id="nn.cross_entropy")
def cross_entropy(logits, target, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", label_smoothing: float = 0.0):
    """logits: (N, C) or (N, C, ...) float; target: (N, ...) int class ids."""
    check(weight is None, "cross_entropy: class weights not yet supported")
    C = logits.shape[1] if logits.ndim > 1 else logits.shape[0]
    expect = (logits.shape[0],) + tuple(logits.shape[2:]) if logits.ndim > 1 else ()
    check(tuple(target.shape) == expect, lambda: (
        f"cross_entropy: target shape {tuple(target.shape)} does not match "
        f"logits {tuple(logits.shape)} — expected {expect} "
        f"(N, d1, ...; the class dim C={C} is dim 1 of logits)"))
    if logits.ndim > 2:
        # (N, C, d1..) -> (N*d1.., C)
        perm = (0,) + tuple(range(2, logits.ndim)) + (1,)
        logits = ops.reshape(ops.transpose(logits, perm), (-1, C))
        target = ops.reshape(target, (-1,))
    logp = ops.log_softmax(logits, -1)
    tgt = ops.convert_element_type(target, dtypes.int32)
    safe_tgt = ops.where(ops.eq(tgt, ignore_index), ops.zeros_like(tgt), tgt)
    picked = ops.squeeze(prims.take_along_axis(logp, ops.unsqueeze(safe_tgt, -1), 1), (1,))
    nll = ops.neg(picked)
    if label_smoothing > 0.0:
        smooth = ops.neg(ops.mean(logp, -1))
        nll = ops.add(ops.mul(nll, 1.0 - label_smoothing), ops.mul(smooth, label_smoothing))
    valid = ops.ne(tgt, ignore_index)
    nll = ops.where(valid, nll, ops.zeros_like(nll))
    if reduction == "none":
        return nll
    if reduction == "sum":
        return ops.sum(nll)
    count = ops.sum(ops.convert_element_type(valid, dtypes.float32))
    return ops.true_divide(ops.sum(nll), ops.maximum(count, 1.0))


@opsymbol(id="nn.sdpa_fwd")
def sdpa_fwd(q, k, v, is_causal: bool = False, scale: float | None = None):
    """Attention forward that also returns the row logsumexp — the
    flash-attention forward contract. Claimable by the Pallas executor; the
    decomposition below is the always-available fallback."""
    E = q.shape[-1]
    L, S = q.shape[-2], k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    qf = ops.convert_element_type(q, dtypes.float32)
    kf = ops.convert_element_type(k, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    scores = ops.mul(ops.matmul(qf, kf.mT), scale)
    if is_causal:
        causal = ops.tril_mask(L, S, 0, device=q.device)
        scores = ops.where(ops.expand_to(causal, scores.shape), scores,
                           ops.full_like(scores, -float("inf")))
    m = ops.amax(scores, -1, keepdim=True)
    e = ops.exp(ops.sub(scores, m))
    l = ops.sum(e, -1, keepdim=True)
    out = ops.matmul(ops.true_divide(e, l), vf)
    lse = ops.add(ops.squeeze(m, -1), ops.log(ops.squeeze(l, -1)))
    return ops.convert_element_type(out, q.dtype), lse


@opsymbol(id="nn.ce_fwd")
def ce_fwd(logits, target, ignore_index: int = -100):
    """Per-row negative log-likelihood + logsumexp (fused-CE forward
    contract; Pallas-claimable). logits: (N, C); target: (N,) int."""
    lf = ops.convert_element_type(logits, dtypes.float32)
    m = ops.amax(lf, -1, keepdim=True)
    lse = ops.add(ops.squeeze(m, -1), ops.log(ops.sum(ops.exp(ops.sub(lf, m)), -1)))
    tgt = ops.convert_element_type(target, dtypes.int32)
    safe_tgt = ops.where(ops.eq(tgt, ignore_index), ops.zeros_like(tgt), tgt)
    picked = ops.squeeze(prims.take_along_axis(lf, ops.unsqueeze(safe_tgt, -1), 1), (1,))
    nll = ops.sub(lse, picked)
    valid = ops.ne(tgt, ignore_index)
    nll = ops.where(valid, nll, ops.zeros_like(nll))
    return nll, lse


@opsymbol(id="nn.sdpa_bwd")
def sdpa_bwd(g, q, k, v, out, lse, is_causal: bool = False, scale: float | None = None):
    """Flash-attention backward contract: recompute probabilities from
    (q, k, lse), produce (dq, dk, dv). Claimable by the Pallas executor;
    this decomposition is the always-available fallback."""
    E = q.shape[-1]
    L, S = q.shape[-2], k.shape[-2]
    scale_v = scale if scale is not None else 1.0 / math.sqrt(E)
    gf = ops.convert_element_type(g, dtypes.float32)
    qf = ops.convert_element_type(q, dtypes.float32)
    kf = ops.convert_element_type(k, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    of = ops.convert_element_type(out, dtypes.float32)
    scores = ops.mul(ops.matmul(qf, kf.mT), scale_v)
    if is_causal:
        causal = ops.tril_mask(L, S, 0, device=q.device)
        scores = ops.where(ops.expand_to(causal, scores.shape), scores,
                           ops.full_like(scores, -float("inf")))
    p = ops.exp(ops.sub(scores, ops.unsqueeze(lse, -1)))
    dv = ops.matmul(p.mT, gf)
    dp = ops.matmul(gf, vf.mT)
    delta = ops.sum(ops.mul(gf, of), -1, keepdim=True)  # rowsum(dO * O)
    ds = ops.mul(ops.mul(p, ops.sub(dp, delta)), scale_v)
    dq = ops.matmul(ds, kf)
    dk = ops.matmul(ds.mT, qf)
    return (ops.convert_element_type(dq, q.dtype),
            ops.convert_element_type(dk, k.dtype),
            ops.convert_element_type(dv, v.dtype))


@opsymbol(id="nn.paged_decode_attention")
def paged_decode_attention(q, k_pages, v_pages, block_tables, lengths,
                           scale: float | None = None):
    """Ragged-batch attention over a block-allocated paged KV cache — the
    serving engine's decode attention (``thunder_tpu/serving/``): every
    request in the batch reads its OWN context length through its OWN block
    table, in one launch, from one shared page pool.

    - ``q``: ``(B, n_heads, T, hd)`` — the T newest positions per request
      (decode T=1; chunked prefill passes the whole chunk).
    - ``k_pages`` / ``v_pages``: ``(kv_heads, num_pages, page_size, hd)`` —
      the shared per-layer page pools.
    - ``block_tables``: ``(B, pages_per_request)`` int32 page ids; entries
      beyond a request's allocation must still be valid pool indices (the
      allocator reserves page 0 as the never-read scratch page).
    - ``lengths``: ``(B,)`` int32 context length per request INCLUDING the
      T new rows — row ``r`` sits at absolute position ``lengths - T + r``
      and attends keys ``j <= lengths - T + r`` (ragged causal masking).

    Head grouping is GQA-contiguous, matching ``models/llama.forward_step``:
    query head ``h`` reads kv head ``h // (n_heads // kv_heads)``.

    The decomposition below (gather pages through the block table, mask,
    softmax) is the always-available XLA fallback — the Pallas executor
    claims the T==1 decode case as a single scalar-prefetch kernel that
    streams each request's pages by block-table lookup, and the kernel
    quarantine / bisection machinery falls back here per-op with equal
    numerics.
    """
    _tensor_like(q, "paged_decode_attention")
    check(q.ndim == 4 and k_pages.ndim == 4 and v_pages.ndim == 4,
          lambda: f"paged_decode_attention: q must be (B, H, T, hd) and pages "
                  f"(kv_heads, P, page, hd); got q {tuple(q.shape)}, "
                  f"k_pages {tuple(k_pages.shape)}")
    B, H, T, hd = q.shape
    KV, P, ps, hd2 = k_pages.shape
    check(hd2 == hd and tuple(v_pages.shape) == tuple(k_pages.shape),
          lambda: f"paged_decode_attention: page pools {tuple(k_pages.shape)} / "
                  f"{tuple(v_pages.shape)} do not match head_dim {hd}")
    check(H % KV == 0,
          lambda: f"paged_decode_attention: n_heads {H} not divisible by "
                  f"kv_heads {KV}")
    check(block_tables.ndim == 2 and block_tables.shape[0] == B
          and lengths.ndim == 1 and lengths.shape[0] == B,
          lambda: f"paged_decode_attention: block_tables {tuple(block_tables.shape)}"
                  f" / lengths {tuple(lengths.shape)} do not match batch {B}")
    n_rep = H // KV
    npg = block_tables.shape[1]
    L = npg * ps
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)

    # gather each request's context from the shared pools via its block
    # table: (KV, P, ps, hd) indexed along the page dim by the flattened
    # (B*npg,) table -> (KV, B, npg*ps, hd) -> (B, KV, L, hd)
    idx = ops.reshape(block_tables, (B * npg,))
    k = ops.transpose(ops.reshape(prims.take(k_pages, idx, 1),
                                  (KV, B, L, hd)), (1, 0, 2, 3))
    v = ops.transpose(ops.reshape(prims.take(v_pages, idx, 1),
                                  (KV, B, L, hd)), (1, 0, 2, 3))
    # grouped-query attention without materializing the expanded cache:
    # fold the group dim into q's row dim (forward_step's GQA recipe)
    qg = ops.reshape(q, (B, KV, n_rep * T, hd))
    qf = ops.convert_element_type(qg, dtypes.float32)
    kf = ops.convert_element_type(k, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    scores = ops.mul(ops.matmul(qf, kf.mT), scale)        # (B, KV, n_rep*T, L)
    scores = ops.reshape(scores, (B, H, T, L))
    # ragged causal mask: key j valid for row r iff j <= lengths - T + r
    col = ops.arange(L)                                   # (L,)
    row_pos = ops.add(ops.unsqueeze(ops.sub(lengths, T), 1),
                      ops.unsqueeze(ops.arange(T), 0))    # (B, T)
    valid = ops.le(ops.unsqueeze(ops.unsqueeze(col, 0), 0),
                   ops.unsqueeze(row_pos, 2))             # (B, T, L)
    neg = ops.full((), float("-inf"), dtype=dtypes.float32)
    scores = ops.where(ops.expand_to(ops.unsqueeze(valid, 1), scores.shape),
                       scores, neg)
    probs = ops.softmax(scores, -1)
    attn = ops.matmul(ops.reshape(probs, (B, KV, n_rep * T, L)), vf)
    return ops.convert_element_type(ops.reshape(attn, (B, H, T, hd)), q.dtype)


def decode_row_write(pool_flat, rows, flat_positions):
    """Scatter every decode slot's K/V row into a flattened page pool in ONE
    replace-semantics scatter — the serving runner's K/V append, shared here
    so the ``nn.attn_subblock`` decomposition and ``serving/runner.py`` emit
    the IDENTICAL op sequence (the block planner's chain matcher and the
    per-op quarantine fallback both depend on that identity).

    ``pool_flat``: (KV, P*ps, hd); ``rows``: (S, KV, 1, hd);
    ``flat_positions``: (S,) int32 of ``page*ps + offset``. Idle slots all
    target position 0 (the reserved scratch page); duplicate indices there
    are benign (any write wins, nobody reads)."""
    S = rows.shape[0]
    src = ops.transpose(ops.squeeze(rows, 2), (1, 0, 2))       # (KV, S, hd)
    idx = ops.expand_to(ops.reshape(flat_positions, (1, S, 1)), src.shape)
    return prims.scatter(pool_flat, idx, src, 1)


_DECODE_T1 = ("decode-only composite (T == 1): every slot contributes one "
              "new row; the chunked-prefill path keeps the unfused ops")


@opsymbol(id="nn.attn_subblock")
def attn_subblock(h, w_norm, wq, wk, wv, wo, cos, sin, k_pages, v_pages,
                  block_tables, lengths, write_pos, *, eps: float = 1e-5,
                  scale: float | None = None):
    """Whole serving attention sub-block of one T==1 decode step as ONE
    claimable composite — the block planner's attention unit
    (``core/fusion_passes.block_fusion_pass`` attention walk)::

        x    = rms_norm(h, w_norm)
        q,k,v= rope(split_heads(x @ wq/wk/wv.T))   # v un-roped
        kp,vp= pools with this step's k/v rows scattered at write_pos
        attn = paged_decode_attention(q, kp, vp, block_tables, lengths)
        out  = merge_heads(attn) @ wo.T            # residual add stays outside

    Returns ``(out, kp, vp)`` — the out-projection (pre-residual; the
    ``h + out`` add belongs to the adjoining MLP sub-block, which is how
    the chaining stage fuses the two into ``nn.decode_layer``) and the
    updated page pools. The decomposition below is EXACTLY the op sequence
    ``serving/runner.py`` emits per layer (that is the numerics contract
    when nothing claims it, and the per-op XLA fallback quarantine/bisection
    recompiles to); the Pallas executor claims it as a single launch with
    the weights streamed through VMEM, the fresh K/V rows patched in from
    VMEM scratch, and block tables / lengths scalar-prefetched.
    """
    _tensor_like(h, "attn_subblock")
    B, T = h.shape[0], h.shape[1]
    check(T == 1, lambda: f"attn_subblock: {_DECODE_T1}; got T={T}")
    KV, P, ps, hd = k_pages.shape
    check(tuple(v_pages.shape) == tuple(k_pages.shape),
          lambda: f"attn_subblock: page pools {tuple(k_pages.shape)} / "
                  f"{tuple(v_pages.shape)} differ")
    H = wq.shape[0] // hd
    check(wq.shape[0] == H * hd and wk.shape[0] == KV * hd
          and tuple(wv.shape) == tuple(wk.shape)
          and wo.shape[1] == H * hd,
          lambda: f"attn_subblock: projection shapes wq {tuple(wq.shape)} / "
                  f"wk {tuple(wk.shape)} / wo {tuple(wo.shape)} do not agree "
                  f"with head_dim {hd}")
    from thunder_tpu.models.llama import _apply_rope

    x = rms_norm(h, w_norm, eps=eps)
    q = ops.transpose(ops.reshape(ops.linear(x, wq), (B, T, H, hd)),
                      (0, 2, 1, 3))
    k = ops.transpose(ops.reshape(ops.linear(x, wk), (B, T, KV, hd)),
                      (0, 2, 1, 3))
    v = ops.transpose(ops.reshape(ops.linear(x, wv), (B, T, KV, hd)),
                      (0, 2, 1, 3))
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    flat = (KV, P * ps, hd)
    paged = (KV, P, ps, hd)
    kp = ops.reshape(decode_row_write(ops.reshape(k_pages, flat), k,
                                      write_pos), paged)
    vp = ops.reshape(decode_row_write(ops.reshape(v_pages, flat), v,
                                      write_pos), paged)
    attn = paged_decode_attention(q, kp, vp, block_tables, lengths,
                                  scale=scale)
    attn = ops.reshape(ops.transpose(attn, (0, 2, 1, 3)), (B, T, H * hd))
    return ops.linear(attn, wo), kp, vp


@opsymbol(id="nn.decode_layer")
def decode_layer(h, attn_norm, wq, wk, wv, wo, cos, sin, k_pages, v_pages,
                 block_tables, lengths, write_pos, mlp_norm, w_gate, w_up,
                 w_down, *, act: str = "silu", eps: float = 1e-5,
                 scale: float | None = None):
    """One whole transformer decode layer (T==1 serving path) as ONE
    claimable composite — the block planner's chaining unit: the attention
    sub-block plus the MLP sub-block, one Pallas launch per layer per
    decoded token when claimed. Returns ``(out, kp, vp)``.

    The decomposition is the two sub-block composites, which gives the
    quarantine/bisection machinery a LAYERED fallback: a quarantined
    ``pallas.decode_layer`` decomposes into ``nn.attn_subblock`` +
    ``nn.mlp_subblock`` (two launches, still fused); quarantining those too
    reaches the fully per-op XLA chain with equal numerics."""
    proj, kp, vp = attn_subblock(h, attn_norm, wq, wk, wv, wo, cos, sin,
                                 k_pages, v_pages, block_tables, lengths,
                                 write_pos, eps=eps, scale=scale)
    out = mlp_subblock(h, proj, mlp_norm, w_gate, w_up, w_down,
                       act=act, eps=eps)
    return out, kp, vp


@opsymbol(id="nn.fp8_linear")
def fp8_linear(a, w, x_scale=None, w_scale=None, bias=None, slot: int = -1):
    """FP8 linear (TransformerEngine analog, reference
    ``thunder/executors/transformer_engineex.py:181,351``): e4m3 quantized
    ``a @ w.T`` with f32 accumulation, dequantized by the scale product.
    Returns ``(out, amax_x, amax_w)`` — the amaxes feed the caller's
    delayed-scaling state update (``thunder_tpu.fp8``). ``x_scale``/
    ``w_scale`` of None selects just-in-time scaling."""
    from thunder_tpu.fp8 import E4M3_MAX

    amax_x = ops.amax(ops.abs(a))
    amax_w = ops.amax(ops.abs(w))
    sx = x_scale if x_scale is not None else ops.true_divide(E4M3_MAX, ops.maximum(amax_x, 1e-12))
    sw = w_scale if w_scale is not None else ops.true_divide(E4M3_MAX, ops.maximum(amax_w, 1e-12))
    aq = ops.convert_element_type(
        ops.clamp(ops.mul(ops.convert_element_type(a, dtypes.float32), sx), -E4M3_MAX, E4M3_MAX),
        dtypes.float8_e4m3fn)
    wq = ops.convert_element_type(
        ops.clamp(ops.mul(ops.convert_element_type(w, dtypes.float32), sw), -E4M3_MAX, E4M3_MAX),
        dtypes.float8_e4m3fn)
    out = prims.dot_general(aq, wq, contract_dims=((a.ndim - 1,), (1,)),
                            preferred_element_type=dtypes.float32)
    out = ops.true_divide(out, ops.mul(sx, sw))
    out = ops.convert_element_type(out, a.dtype)
    if bias is not None:
        out = ops.add(out, bias)
    # every (re)trace of this composite — initial emission, autograd replay,
    # checkpoint recompute — re-records its live amax proxies with the active
    # delayed-scaling context (last write wins)
    from thunder_tpu.fp8 import current_fp8

    ctx = current_fp8()
    if ctx is not None and slot >= 0:
        ctx._record(slot, amax_x, amax_w)
    return out, amax_x, amax_w


@opsymbol(id="nn.scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                                 is_causal: bool = False, scale: float | None = None):
    """q,k,v: (..., L, E) / (..., S, E). Decomposes to softmax(q k^T / sqrt(E)) v;
    the Pallas flash-attention executor claims this symbol on TPU. Under an
    active context-parallel scope, lowers to ring attention over the mesh
    axis (sequence sharded; K/V rotate via ppermute)."""
    _tensor_like(q, "scaled_dot_product_attention")
    from thunder_tpu.distributed import current_cp

    cp = current_cp()
    if cp is not None and attn_mask is None and dropout_p == 0.0:
        from thunder_tpu.distributed.ring import ring_attention

        axis, size = cp
        return ring_attention(q, k, v, axis, size, is_causal, scale)
    E = q.shape[-1]
    L, S = q.shape[-2], k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    qf = ops.convert_element_type(q, dtypes.float32)
    kf = ops.convert_element_type(k, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    scores = ops.mul(ops.matmul(qf, kf.mT), scale)
    if is_causal:
        check(attn_mask is None, "cannot pass both is_causal and attn_mask")
        causal = ops.tril_mask(L, S, 0, device=q.device)
        scores = ops.where(ops.expand_to(causal, scores.shape), scores,
                           ops.full_like(scores, -float("inf")))
    if attn_mask is not None:
        if attn_mask.dtype.is_bool:
            scores = ops.where(ops.expand_to(attn_mask, scores.shape), scores,
                               ops.full_like(scores, -float("inf")))
        else:
            scores = ops.add(scores, attn_mask)
    probs = ops.softmax(scores, -1)
    if dropout_p > 0.0:
        probs = dropout(probs, dropout_p)
    out = ops.matmul(probs, vf)
    return ops.convert_element_type(out, q.dtype)


# ---------------------------------------------------------------------------
# flash-style custom VJP rules: save (q, k, v, out, lse) and recompute the
# attention matrix / softmax in backward instead of saving (B,H,L,S) probs.
# This is the memory contract of the reference's fused-attention executors
# (sdpaex/cudnnex fwd+bwd pairs, ``thunder/executors/sdpaex.py:239,312``),
# expressed as a trace-level grad rule; the fwd symbol is Pallas-claimable.
# ---------------------------------------------------------------------------

from thunder_tpu.core.transforms import register_vjp  # noqa: E402
from thunder_tpu.core.proxies import TensorProxy  # noqa: E402


@register_vjp("nn.scaled_dot_product_attention")
def _sdpa_vjp(q, k, v, attn_mask=None, dropout_p: float = 0.0, is_causal: bool = False,
              scale: float | None = None):
    from thunder_tpu.distributed import current_cp

    if attn_mask is not None or dropout_p > 0.0 or current_cp() is not None:
        return NotImplemented  # fall back to differentiating the decomposition
    out, lse = sdpa_fwd(q, k, v, is_causal, scale)

    def pullback(g):
        dq, dk, dv = sdpa_bwd(g, q, k, v, out, lse, is_causal, scale)
        return [(q, dq), (k, dk), (v, dv)]

    return out, pullback


@register_vjp("nn.rms_norm")
def _rms_norm_vjp(a, weight=None, eps: float = 1e-5, dim: int = -1):
    """Keep ``nn.rms_norm`` a composite in training traces (the autodiff
    replay otherwise decomposes it to prims, which hides it from both the
    Pallas claim and the epilogue fusion pattern). Saves only (a, weight) —
    the backward recomputes the row statistics, like the flash-attention
    rules recompute the softmax."""
    if dim not in (-1, a.ndim - 1):
        return NotImplemented
    out = rms_norm(a, weight, eps=eps, dim=dim)

    def pullback(g):
        # same dtype policy as the forward composite: widen to f32 only for
        # half precision — f32 stays f32, and f64 (x64 mode) keeps full
        # precision instead of silently narrowing
        wide = dtypes.float32 if a.dtype in (dtypes.float16, dtypes.bfloat16) else a.dtype
        x = ops.convert_element_type(a, wide)
        g32 = ops.convert_element_type(g, wide)
        ms = ops.mean(ops.mul(x, x), -1, keepdim=True)
        r = ops.rsqrt(ops.add(ms, eps))
        xhat = ops.mul(x, r)
        if weight is not None:
            gxhat = ops.mul(g32, ops.convert_element_type(weight, wide))
        else:
            gxhat = g32
        # d/dx of x·(mean(x²)+eps)^(-1/2): r·(ĝ − x̂·mean(ĝ·x̂))
        proj = ops.mean(ops.mul(gxhat, xhat), -1, keepdim=True)
        da = ops.mul(r, ops.sub(gxhat, ops.mul(xhat, proj)))
        pairs = [(a, ops.convert_element_type(da, a.dtype))]
        if weight is not None and isinstance(weight, TensorProxy):
            lead = tuple(range(a.ndim - 1))
            dw = ops.mul(g32, xhat) if not lead else ops.sum(ops.mul(g32, xhat), lead)
            pairs.append((weight, ops.convert_element_type(dw, weight.dtype)))
        return pairs

    return out, pullback


@register_vjp("nn.mlp_subblock")
def _mlp_subblock_vjp(residual, x, w_norm, w_gate, w_up, w_down, *,
                      act: str = "silu", eps: float = 1e-5):
    """Keep the planned sub-block megakernel claimable under autodiff: the
    forward stays the ONE ``nn.mlp_subblock`` composite (saving only its
    inputs), and the pullback emits the equally-claimable
    ``nn.mlp_subblock_bwd`` — forward and backward are each a single
    Pallas-claimable unit, and neither materializes the chain's interior
    activations outside VMEM (the sdpa fwd/bwd memory contract applied to
    the MLP sub-block)."""
    out = mlp_subblock(residual, x, w_norm, w_gate, w_up, w_down, act=act, eps=eps)

    def pullback(g):
        dh, dwn, dwg, dwu, dwd = mlp_subblock_bwd(
            g, residual, x, w_norm, w_gate, w_up, w_down, act=act, eps=eps)
        pairs = [(residual, dh), (x, dh), (w_gate, dwg), (w_up, dwu), (w_down, dwd)]
        if w_norm is not None and isinstance(w_norm, TensorProxy):
            pairs.append((w_norm, dwn))
        return pairs

    return out, pullback


@register_vjp("nn.fp8_linear")
def _fp8_linear_vjp(a, w, x_scale=None, w_scale=None, bias=None, slot: int = -1):
    """TE-recipe backward (reference ``transformer_engineex.py:397-447``):
    dgrad = e5m2-quantized cotangent x e4m3 weight; wgrad accumulated in
    f32 from unquantized operands (TE's higher-precision wgrad default)."""
    from thunder_tpu.fp8 import E4M3_MAX, E5M2_MAX

    out, amax_x, amax_w = fp8_linear(a, w, x_scale, w_scale, bias, slot)
    sw = w_scale if w_scale is not None else ops.true_divide(E4M3_MAX, ops.maximum(amax_w, 1e-12))

    def pullback(g):
        gy = g[0] if isinstance(g, (tuple, list)) else g
        if gy is None:
            return []
        gf = ops.convert_element_type(gy, dtypes.float32)
        # dgrad in fp8: e5m2 cotangent (JIT scale) x e4m3 weight
        amax_g = ops.amax(ops.abs(gf))
        sg = ops.true_divide(E5M2_MAX, ops.maximum(amax_g, 1e-12))
        gq = ops.convert_element_type(
            ops.clamp(ops.mul(gf, sg), -E5M2_MAX, E5M2_MAX), dtypes.float8_e5m2)
        wq = ops.convert_element_type(
            ops.clamp(ops.mul(ops.convert_element_type(w, dtypes.float32), sw),
                      -E4M3_MAX, E4M3_MAX), dtypes.float8_e4m3fn)
        da = prims.dot_general(gq, wq, contract_dims=((gy.ndim - 1,), (0,)),
                               preferred_element_type=dtypes.float32)
        da = ops.true_divide(da, ops.mul(sg, sw))
        # wgrad in f32: flatten leading dims, g2^T @ a2
        N = 1
        for d in gy.shape[:-1]:
            N *= d
        g2 = ops.reshape(gf, (N, gy.shape[-1]))
        a2 = ops.reshape(ops.convert_element_type(a, dtypes.float32), (N, a.shape[-1]))
        dw = prims.dot_general(g2, a2, contract_dims=((0,), (0,)),
                               preferred_element_type=dtypes.float32)
        pairs = [(a, ops.convert_element_type(da, a.dtype)),
                 (w, ops.convert_element_type(dw, w.dtype))]
        if bias is not None and isinstance(bias, TensorProxy):
            db = ops.sum(g2, 0)
            pairs.append((bias, ops.convert_element_type(db, bias.dtype)))
        return pairs

    return (out, amax_x, amax_w), pullback


@register_vjp("nn.cross_entropy")
def _cross_entropy_vjp(logits, target, weight=None, ignore_index: int = -100,
                       reduction: str = "mean", label_smoothing: float = 0.0):
    if weight is not None or label_smoothing > 0.0 or logits.ndim != 2:
        return NotImplemented
    nll, lse = ce_fwd(logits, target, ignore_index)
    tgt = ops.convert_element_type(target, dtypes.int32)
    valid = ops.ne(tgt, ignore_index)
    validf = ops.convert_element_type(valid, dtypes.float32)
    count = ops.maximum(ops.sum(validf), 1.0)
    if reduction == "mean":
        loss = ops.true_divide(ops.sum(nll), count)
    elif reduction == "sum":
        loss = ops.sum(nll)
    elif reduction == "none":
        loss = nll
    else:
        return NotImplemented

    def pullback(g):
        C = logits.shape[-1]
        lf = ops.convert_element_type(logits, dtypes.float32)
        p = ops.exp(ops.sub(lf, ops.unsqueeze(lse, -1)))  # softmax rows
        safe_tgt = ops.where(ops.eq(tgt, ignore_index), ops.zeros_like(tgt), tgt)
        onehot = ops.convert_element_type(one_hot(safe_tgt, C), dtypes.float32)
        if reduction == "mean":
            row_scale = ops.mul(ops.true_divide(validf, count), g)
        elif reduction == "sum":
            row_scale = ops.mul(validf, g)
        else:
            row_scale = ops.mul(validf, g)
        dlogits = ops.mul(ops.sub(p, onehot), ops.unsqueeze(row_scale, -1))
        return [(logits, ops.convert_element_type(dlogits, logits.dtype))]

    return loss, pullback


# ---------------------------------------------------------------------------
# additional losses (reference: thunder/torch/__init__.py loss section)
# ---------------------------------------------------------------------------

def _reduce_loss(per_elem, reduction: str):
    if reduction == "none":
        return per_elem
    if reduction == "sum":
        return ops.sum(per_elem)
    check(reduction == "mean", lambda: f"unknown reduction {reduction!r}")
    return ops.mean(per_elem)


@opsymbol(id="nn.l1_loss")
def l1_loss(input, target, reduction: str = "mean"):
    return _reduce_loss(ops.abs(ops.sub(input, target)), reduction)


@opsymbol(id="nn.smooth_l1_loss")
def smooth_l1_loss(input, target, reduction: str = "mean", beta: float = 1.0):
    d = ops.abs(ops.sub(input, target))
    per = ops.where(ops.lt(d, beta),
                    ops.true_divide(ops.mul(ops.mul(d, d), 0.5), beta),
                    ops.sub(d, 0.5 * beta))
    return _reduce_loss(per, reduction)


@opsymbol(id="nn.huber_loss")
def huber_loss(input, target, reduction: str = "mean", delta: float = 1.0):
    d = ops.abs(ops.sub(input, target))
    per = ops.where(ops.lt(d, delta),
                    ops.mul(ops.mul(d, d), 0.5),
                    ops.mul(delta, ops.sub(d, 0.5 * delta)))
    return _reduce_loss(per, reduction)


@opsymbol(id="nn.binary_cross_entropy")
def binary_cross_entropy(input, target, weight=None, reduction: str = "mean"):
    eps = 1e-12
    per = ops.neg(ops.add(ops.mul(target, ops.log(ops.clamp(input, min=eps))),
                          ops.mul(ops.sub(1.0, target),
                                  ops.log(ops.clamp(ops.sub(1.0, input), min=eps)))))
    if weight is not None:
        per = ops.mul(per, weight)
    return _reduce_loss(per, reduction)


@opsymbol(id="nn.binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(input, target, weight=None, pos_weight=None,
                                     reduction: str = "mean"):
    # stable: max(x,0) - x*t + log(1+exp(-|x|)), with optional pos_weight
    neg_abs = ops.neg(ops.abs(input))
    softplus_term = ops.log1p(ops.exp(neg_abs))
    if pos_weight is not None:
        log_weight = ops.add(1.0, ops.mul(ops.sub(pos_weight, 1.0), target))
        per = ops.add(ops.sub(ops.clamp(input, min=0.0), ops.mul(input, target)),
                      ops.mul(log_weight, softplus_term))
    else:
        per = ops.add(ops.sub(ops.clamp(input, min=0.0), ops.mul(input, target)),
                      softplus_term)
    if weight is not None:
        per = ops.mul(per, weight)
    return _reduce_loss(per, reduction)


@opsymbol(id="nn.kl_div")
def kl_div(input, target, reduction: str = "mean", log_target: bool = False):
    """input is log-probabilities (torch convention)."""
    if log_target:
        per = ops.mul(ops.exp(target), ops.sub(target, input))
    else:
        per = ops.xlogy(target, target)
        per = ops.sub(per, ops.mul(target, input))
    return _reduce_loss(per, reduction)


@opsymbol(id="nn.nll_loss")
def nll_loss(logp, target, weight=None, ignore_index: int = -100,
             reduction: str = "mean"):
    _tensor_like(logp, "nll_loss")
    check(weight is None, "nll_loss: class weights unsupported")
    tgt = ops.reshape(target, (-1,)) if target.ndim > 1 else target
    lp = ops.reshape(logp, (-1, logp.shape[-1])) if logp.ndim > 2 else logp
    safe = ops.where(ops.ne(tgt, ignore_index), tgt, ops.zeros_like(tgt))
    picked = ops.neg(ops.squeeze(ops.gather(lp, 1, ops.unsqueeze(safe, 1)), 1))
    valid = ops.ne(tgt, ignore_index)
    picked = ops.where(valid, picked, ops.zeros_like(picked))
    if reduction == "none":
        return ops.reshape(picked, tuple(target.shape))
    total = ops.sum(picked)
    if reduction == "sum":
        return total
    return ops.true_divide(total, ops.sum(ops.convert_element_type(valid, picked.dtype)))


# ---------------------------------------------------------------------------
# pooling — decomposed into static strided slices + elementwise reductions
# (fully differentiable through existing prims; XLA fuses the k*k slice
# reads into one windowed reduce on TPU)
# ---------------------------------------------------------------------------

def _pool_windows(a, kernel_size, stride, padding, pad_value, nd=2):
    """Sliding windows over the last ``nd`` spatial dims (1-, 2- or 3-d
    pooling share this decomposition)."""
    import itertools

    ks = (kernel_size,) * nd if isinstance(kernel_size, int) else tuple(kernel_size)
    if stride is None:
        stride = ks
    ss = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    ps = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    if any(ps):
        cfg = tuple((0, 0, 0) for _ in range(a.ndim - nd)) + tuple((p, p, 0) for p in ps)
        a = ops.pad(a, cfg, value=pad_value)
    outs = [(a.shape[a.ndim - nd + i] - ks[i]) // ss[i] + 1 for i in range(nd)]
    windows = []
    for offs in itertools.product(*(range(k) for k in ks)):
        idx = (Ellipsis,) + tuple(
            slice(offs[i], offs[i] + (outs[i] - 1) * ss[i] + 1, ss[i]) for i in range(nd))
        windows.append(ops.getitem(a, idx))
    return windows, math.prod(ks)


@opsymbol(id="nn.max_pool2d")
def max_pool2d(a, kernel_size, stride=None, padding=0):
    _tensor_like(a, "max_pool2d")
    windows, _ = _pool_windows(a, kernel_size, stride, padding, float("-inf"))
    out = windows[0]
    for w in windows[1:]:
        out = ops.maximum(out, w)
    return out


@opsymbol(id="nn.avg_pool2d")
def avg_pool2d(a, kernel_size, stride=None, padding=0, count_include_pad: bool = True):
    _tensor_like(a, "avg_pool2d")
    check(count_include_pad or padding == 0, "avg_pool2d: count_include_pad=False unsupported")
    windows, n = _pool_windows(a, kernel_size, stride, padding, 0.0)
    out = windows[0]
    for w in windows[1:]:
        out = ops.add(out, w)
    return ops.true_divide(out, float(n))


@opsymbol(id="nn.max_pool1d")
def max_pool1d(a, kernel_size, stride=None, padding=0):
    _tensor_like(a, "max_pool1d")
    windows, _ = _pool_windows(a, kernel_size, stride, padding, float("-inf"), nd=1)
    out = windows[0]
    for w in windows[1:]:
        out = ops.maximum(out, w)
    return out


@opsymbol(id="nn.max_pool3d")
def max_pool3d(a, kernel_size, stride=None, padding=0):
    _tensor_like(a, "max_pool3d")
    windows, _ = _pool_windows(a, kernel_size, stride, padding, float("-inf"), nd=3)
    out = windows[0]
    for w in windows[1:]:
        out = ops.maximum(out, w)
    return out


@opsymbol(id="nn.avg_pool1d")
def avg_pool1d(a, kernel_size, stride=None, padding=0, count_include_pad: bool = True):
    _tensor_like(a, "avg_pool1d")
    check(count_include_pad or padding == 0, "avg_pool1d: count_include_pad=False unsupported")
    windows, n = _pool_windows(a, kernel_size, stride, padding, 0.0, nd=1)
    out = windows[0]
    for w in windows[1:]:
        out = ops.add(out, w)
    return ops.true_divide(out, float(n))


@opsymbol(id="nn.avg_pool3d")
def avg_pool3d(a, kernel_size, stride=None, padding=0, count_include_pad: bool = True):
    _tensor_like(a, "avg_pool3d")
    check(count_include_pad or padding == 0, "avg_pool3d: count_include_pad=False unsupported")
    windows, n = _pool_windows(a, kernel_size, stride, padding, 0.0, nd=3)
    out = windows[0]
    for w in windows[1:]:
        out = ops.add(out, w)
    return ops.true_divide(out, float(n))


@opsymbol(id="nn.adaptive_avg_pool2d")
def adaptive_avg_pool2d(a, output_size):
    _tensor_like(a, "adaptive_avg_pool2d")
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)
    H, W = a.shape[-2], a.shape[-1]
    check(H % oh == 0 and W % ow == 0,
          lambda: f"adaptive_avg_pool2d: input {H}x{W} not divisible by output {oh}x{ow}")
    r = ops.reshape(a, tuple(a.shape[:-2]) + (oh, H // oh, ow, W // ow))
    return ops.mean(r, dim=(-3, -1))


@opsymbol(id="nn.instance_norm")
def instance_norm(a, weight=None, bias=None, eps: float = 1e-5):
    _tensor_like(a, "instance_norm")
    dims = tuple(range(2, a.ndim))
    var, mean = ops.var_mean(a, dim=dims, correction=0, keepdim=True)
    out = ops.true_divide(ops.sub(a, mean), ops.sqrt(ops.add(var, eps)))
    bshape = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    if weight is not None:
        out = ops.mul(out, ops.reshape(weight, bshape))
    if bias is not None:
        out = ops.add(out, ops.reshape(bias, bshape))
    return out


@opsymbol(id="nn.pixel_shuffle")
def pixel_shuffle(a, upscale_factor: int):
    _tensor_like(a, "pixel_shuffle")
    r = upscale_factor
    B_dims = tuple(a.shape[:-3])
    C, H, W = a.shape[-3], a.shape[-2], a.shape[-1]
    check(C % (r * r) == 0, "pixel_shuffle: channels not divisible by r^2")
    oc = C // (r * r)
    x = ops.reshape(a, B_dims + (oc, r, r, H, W))
    nb = len(B_dims)
    x = ops.transpose(x, tuple(range(nb)) + (nb, nb + 3, nb + 1, nb + 4, nb + 2))
    return ops.reshape(x, B_dims + (oc, H * r, W * r))


@opsymbol(id="nn.interpolate_nearest")
def interpolate_nearest(a, scale_factor: int):
    """Nearest-neighbor upsampling by an integer factor over the last two dims."""
    _tensor_like(a, "interpolate_nearest")
    s = int(scale_factor)
    check(s >= 1, lambda: f"interpolate_nearest: scale_factor must be >= 1, got {s}")
    out = a
    out = ops.movedim(out, -2, 0)
    out = ops.repeat_interleave_dim0(out, s)
    out = ops.movedim(out, 0, -2)
    out = ops.movedim(out, -1, 0)
    out = ops.repeat_interleave_dim0(out, s)
    return ops.movedim(out, 0, -1)


def _default_ce_chunk(V: int) -> int:
    """Fewer, larger matmuls pipeline better on the MXU (measured r5:
    113.8 -> 99.7 ms fwd+bwd at N=16k, V=32k); big vocabs keep the smaller
    chunk so live f32 logits stay ~0.5 GB at bench N. Forward and VJP must
    agree (the VJP recomputes per chunk against the forward's lse)."""
    return 16384 if V <= 65536 else 8192


@opsymbol(id="nn.fused_linear_cross_entropy")
def fused_linear_cross_entropy(h, w, target, *, chunk: int | None = None,
                               ignore_index: int = -100):
    """Mean softmax-cross-entropy of ``h @ w.T`` computed one vocab chunk at
    a time — the (N, V) logits are NEVER materialized (live memory is
    O(N * chunk)); the custom VJP below recomputes per chunk in backward.

    Beyond the reference: its fused-CE executors (apex/triton,
    ``thunder/executors/apex_entropyex.py:99``) still take materialized
    logits; fusing the lm_head projection removes the dominant activation
    of large-vocab training (N*V f32 — e.g. 1 GB at N=2048, V=128k).

    h: (N, D) hidden states; w: (V, D) head weight; target: (N,) int ids.
    """
    N, D = h.shape
    V = w.shape[0]
    if chunk is None:
        chunk = _default_ce_chunk(V)
    tgt = ops.convert_element_type(target, dtypes.int32)

    m = ops.full((N,), float("-inf"), dtype=dtypes.float32)
    s = ops.full((N,), 0.0, dtype=dtypes.float32)
    picked = ops.full((N,), 0.0, dtype=dtypes.float32)
    for c0 in range(0, V, chunk):
        cw = min(chunk, V - c0)
        wc = ops.narrow(w, 0, c0, cw)
        # operands stay in the MODEL dtype (bf16 in training — full MXU
        # rate; f32 operands would halve v5e matmul throughput, measured
        # r5 breakdown: the CE region sat at ~58% MFU), accumulation is
        # f32 via preferred_element_type — the standard large-vocab recipe
        lg = prims.dot_general(h, wc, contract_dims=((1,), (1,)),
                               preferred_element_type=dtypes.float32)
        mc = ops.amax(lg, -1)
        m_new = ops.maximum(m, mc)
        alpha = ops.exp(ops.sub(m, m_new))
        e = ops.exp(ops.sub(lg, ops.unsqueeze(m_new, 1)))
        s = ops.add(ops.mul(s, alpha), ops.sum(e, -1))
        m = m_new
        idx = ops.sub(tgt, c0)
        valid = ops.logical_and(ops.ge(idx, 0), ops.lt(idx, cw))
        safe = ops.clamp(idx, 0, cw - 1)
        pc = ops.squeeze(prims.take_along_axis(lg, ops.unsqueeze(safe, 1), 1), (1,))
        picked = ops.add(picked, ops.where(valid, pc, ops.zeros_like(pc)))

    lse = ops.add(m, ops.log(s))
    nll = ops.sub(lse, picked)
    ok = ops.ne(tgt, ignore_index)
    nll = ops.where(ok, nll, ops.zeros_like(nll))
    count = ops.maximum(ops.sum(ops.convert_element_type(ok, dtypes.float32)), 1.0)
    return ops.true_divide(ops.sum(nll), count), lse


@register_vjp("nn.fused_linear_cross_entropy")
def _flce_vjp(h, w, target, *, chunk: int | None = None, ignore_index: int = -100):
    loss, lse = fused_linear_cross_entropy(h, w, target, chunk=chunk,
                                           ignore_index=ignore_index)
    N, D = h.shape
    V = w.shape[0]
    if chunk is None:
        chunk = _default_ce_chunk(V)  # MUST mirror the forward (shared lse)

    def pullback(g):
        gl, glse = (g[0], g[1]) if isinstance(g, (tuple, list)) else (g, None)
        if gl is None and glse is None:
            return []
        tgt = ops.convert_element_type(target, dtypes.int32)
        hf = ops.convert_element_type(h, dtypes.float32)
        ok = ops.ne(tgt, ignore_index)
        okf = ops.convert_element_type(ok, dtypes.float32)
        count = ops.maximum(ops.sum(okf), 1.0)
        # per-row scale for the nll term: d(mean nll)/d(logit) rows;
        # ignored rows contribute 0
        if gl is not None:
            gs = ops.true_divide(ops.convert_element_type(gl, dtypes.float32), count)
            srow = ops.mul(okf, gs)                                 # (N,)
        else:
            srow = ops.full((N,), 0.0, dtype=dtypes.float32)
        # the lse output is differentiable too (z-loss etc.): d lse/d logit
        # is the softmax row, so its cotangent simply adds to the softmax
        # coefficient (the one-hot term belongs to the nll alone)
        coef = srow if glse is None else             ops.add(srow, ops.convert_element_type(glse, dtypes.float32))
        dh = ops.full((N, D), 0.0, dtype=dtypes.float32)
        dw_chunks = []
        for c0 in range(0, V, chunk):
            cw = min(chunk, V - c0)
            wc = ops.narrow(w, 0, c0, cw)
            lg = prims.dot_general(h, wc, contract_dims=((1,), (1,)),
                                   preferred_element_type=dtypes.float32)
            p = ops.exp(ops.sub(lg, ops.unsqueeze(lse, 1)))         # (N, cw) softmax
            ps = ops.mul(p, ops.unsqueeze(coef, 1))
            # d(logits) cast to the model dtype before the grad matmuls
            # (bf16 operands, f32 accumulation — same recipe as forward;
            # the end results are cast to h/w dtype anyway)
            psc = ops.convert_element_type(ps, w.dtype)
            # softmax part: dh += ps @ wc; dw_c = ps^T @ h_scaled? No —
            # dw_c = ps^T @ h (h unscaled: ps already carries the row scale)
            dh = ops.add(dh, prims.dot_general(psc, wc, contract_dims=((1,), (0,)),
                                               preferred_element_type=dtypes.float32))
            dw_c = prims.dot_general(psc, h, contract_dims=((0,), (0,)),
                                     preferred_element_type=dtypes.float32)  # (cw, D)
            # one-hot part: rows whose target lives in this chunk
            idx = ops.sub(tgt, c0)
            valid = ops.logical_and(ops.ge(idx, 0), ops.lt(idx, cw))
            safe = ops.clamp(idx, 0, cw - 1)
            vrow = ops.mul(srow, ops.convert_element_type(valid, dtypes.float32))
            # dh -= wc[target] * srow   (rows with target in chunk)
            dh = ops.sub(dh, ops.mul(prims.take(wc, safe, 0), ops.unsqueeze(vrow, 1)))
            # dw_c[target] -= h * srow
            neg_rows = ops.mul(hf, ops.unsqueeze(ops.neg(vrow), 1))
            dw_c = prims.index_add(dw_c, safe, neg_rows, 0)
            dw_chunks.append(dw_c)
        dw = ops.cat(dw_chunks, 0)
        return [(h, ops.convert_element_type(dh, h.dtype)),
                (w, ops.convert_element_type(dw, w.dtype))]

    return (loss, lse), pullback


@opsymbol(id="nn.group_norm")
def group_norm(a, num_groups: int, weight=None, bias=None, eps: float = 1e-5):
    """GroupNorm over (N, C, *spatial) — reference
    ``thunder/torch/__init__.py`` group_norm; first-class nn id so executors
    can claim a fused kernel for it."""
    _tensor_like(a, "group_norm")
    n, c = a.shape[0], a.shape[1]
    check(c % num_groups == 0, "group_norm: channels not divisible by groups")
    grouped = ops.reshape(a, (n, num_groups, c // num_groups) + tuple(a.shape[2:]))
    dims = tuple(range(2, grouped.ndim))
    var, mean = ops.var_mean(grouped, dim=dims, correction=0, keepdim=True)
    out = ops.true_divide(ops.sub(grouped, mean), ops.sqrt(ops.add(var, eps)))
    out = ops.reshape(out, tuple(a.shape))
    bshape = (1, c) + (1,) * (a.ndim - 2)
    if weight is not None:
        out = ops.mul(out, ops.reshape(weight, bshape))
    if bias is not None:
        out = ops.add(out, ops.reshape(bias, bshape))
    return out


@opsymbol(id="nn.batch_norm")
def batch_norm(a, running_mean=None, running_var=None, weight=None, bias=None,
               training: bool = False, momentum: float = 0.1, eps: float = 1e-5):
    """Functional BatchNorm: returns ``(out, new_stats)`` where ``new_stats``
    is ``(new_running_mean, new_running_var)`` in training mode with stats
    provided, else None — running statistics are explicit state (no module
    mutation; the torch dialect's F.batch_norm adapter rebinds buffer
    wrappers from this return)."""
    _tensor_like(a, "batch_norm")
    C = int(a.shape[1]) if a.ndim > 1 else int(a.shape[0])
    for nm, st in (("running_mean", running_mean), ("running_var", running_var),
                   ("weight", weight), ("bias", bias)):
        check(st is None or (getattr(st, "ndim", 0) == 1
                             and int(st.shape[0]) == C),
              lambda nm=nm, st=st: f"batch_norm: {nm} must be shape ({C},), "
              f"got {tuple(getattr(st, 'shape', ()))}")
    dims = (0,) + tuple(range(2, a.ndim))
    if training or running_mean is None:
        var, mean = ops.var_mean(a, dim=dims, correction=0, keepdim=False)
    else:
        mean, var = running_mean, running_var
    bshape = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    out = ops.true_divide(ops.sub(a, ops.reshape(mean, bshape)),
                          ops.sqrt(ops.add(ops.reshape(var, bshape), eps)))
    if weight is not None:
        out = ops.mul(out, ops.reshape(weight, bshape))
    if bias is not None:
        out = ops.add(out, ops.reshape(bias, bshape))
    new_stats = None
    if training and running_mean is not None:
        n = 1
        for d in dims:
            n *= a.shape[d]
        unbiased_var = ops.mul(var, float(n) / max(n - 1, 1))
        new_mean = ops.add(ops.mul(running_mean, 1 - momentum), ops.mul(mean, momentum))
        new_var = ops.add(ops.mul(running_var, 1 - momentum), ops.mul(unbiased_var, momentum))
        new_stats = (new_mean, new_var)
    return out, new_stats


# ---------------------------------------------------------------------------
# round 3: grid_sample + ctc_loss (reference thunder/torch F.* coverage)
# ---------------------------------------------------------------------------

@opsymbol(id="nn.grid_sample")
def grid_sample(input, grid, mode: str = "bilinear", padding_mode: str = "zeros",
                align_corners: bool = False):
    """4-D ``F.grid_sample``: sample ``input`` (N,C,H,W) at normalized
    ``grid`` (N,Ho,Wo,2) coordinates. TPU-first: the four corner reads are
    flat gathers over H*W (one fused gather per corner, no scatter/loops);
    differentiable in both ``input`` and ``grid`` (bilinear mode)."""
    check(input.ndim == 4 and grid.ndim == 4 and grid.shape[-1] == 2,
          lambda: f"grid_sample: expected input (N,C,H,W) and grid (N,Ho,Wo,2), "
                  f"got {tuple(input.shape)} and {tuple(grid.shape)}")
    check(mode in ("bilinear", "nearest"),
          lambda: f"grid_sample: unsupported mode {mode!r}")
    check(padding_mode in ("zeros", "border"),
          lambda: f"grid_sample: unsupported padding_mode {padding_mode!r}")
    check(input.shape[0] == grid.shape[0],
          lambda: f"grid_sample: batch mismatch {input.shape[0]} vs {grid.shape[0]}")
    N, C, H, W = input.shape
    _, Ho, Wo, _ = grid.shape
    gx = ops.squeeze(ops.narrow(grid, 3, 0, 1), 3)  # (N,Ho,Wo) x in [-1,1]
    gy = ops.squeeze(ops.narrow(grid, 3, 1, 1), 3)

    def unnorm(g, size):
        if align_corners:
            return ops.mul(ops.add(g, 1.0), (size - 1) / 2.0)
        return ops.true_divide(ops.sub(ops.mul(ops.add(g, 1.0), float(size)), 1.0), 2.0)

    x = unnorm(gx, W)
    y = unnorm(gy, H)
    inp_flat = ops.reshape(input, (N, C, H * W))

    def read(ix, iy):
        """Gather input at integer (iy, ix); returns ((N,C,Ho,Wo), inbounds)."""
        inb = ops.logical_and(
            ops.logical_and(ops.ge(ix, 0), ops.le(ix, W - 1)),
            ops.logical_and(ops.ge(iy, 0), ops.le(iy, H - 1)))
        cx = ops.clamp(ix, 0, W - 1)
        cy = ops.clamp(iy, 0, H - 1)
        flat = ops.reshape(ops.add(ops.mul(cy, W), cx), (N, 1, Ho * Wo))
        idx = ops.expand(flat, (N, C, Ho * Wo))
        vals = ops.reshape(ops.gather(inp_flat, 2, idx), (N, C, Ho, Wo))
        return vals, ops.reshape(inb, (N, 1, Ho, Wo))

    def masked(vals, inb):
        if padding_mode == "zeros":
            return ops.mul(vals, ops.convert_element_type(inb, vals.dtype))
        return vals  # border: clamped read is already the border value

    to_i = lambda v: ops.convert_element_type(v, dtypes.int32)
    if mode == "nearest":
        # torch's kernel uses std::nearbyint — round half to even; ops.round
        # (lax round-to-nearest-even) matches it exactly on .5 boundaries
        vals, inb = read(to_i(ops.round(x)), to_i(ops.round(y)))
        return masked(vals, inb)
    x0f, y0f = ops.floor(x), ops.floor(y)
    wx = ops.reshape(ops.sub(x, x0f), (N, 1, Ho, Wo))
    wy = ops.reshape(ops.sub(y, y0f), (N, 1, Ho, Wo))
    x0, y0 = to_i(x0f), to_i(y0f)
    x1, y1 = ops.add(x0, 1), ops.add(y0, 1)
    v00 = masked(*read(x0, y0))
    v01 = masked(*read(x1, y0))
    v10 = masked(*read(x0, y1))
    v11 = masked(*read(x1, y1))
    one = 1.0
    return ops.add(
        ops.add(ops.mul(v00, ops.mul(ops.sub(one, wx), ops.sub(one, wy))),
                ops.mul(v01, ops.mul(wx, ops.sub(one, wy)))),
        ops.add(ops.mul(v10, ops.mul(ops.sub(one, wx), wy)),
                ops.mul(v11, ops.mul(wx, wy))))


# log-space "impossible" marker: a large FINITE negative (optax-style).
# A true -inf would NaN the VJP (0 * inf in the where/exp pullbacks);
# exp(_CTC_LOG_EPS - x) is exactly 0.0 in f32 for any realistic x.
_CTC_LOG_EPS = -1e5


def _safe_lse(parts):
    """logsumexp over same-shape tensors padded with _CTC_LOG_EPS."""
    m = parts[0]
    for p in parts[1:]:
        m = ops.maximum(m, p)
    s = None
    for p in parts:
        e = ops.exp(ops.sub(p, m))
        s = e if s is None else ops.add(s, e)
    return ops.add(m, ops.log(s))


@opsymbol(id="nn.ctc_loss")
def ctc_loss(log_probs, targets, input_lengths, target_lengths, blank: int = 0,
             reduction: str = "mean", zero_infinity: bool = False):
    """CTC loss (``F.ctc_loss``): the standard alpha recursion over the
    blank-extended target, expressed as a statically-unrolled scan of
    batched gather/logsumexp steps — every step is a (B, 2S+1) vector op,
    so XLA fuses the whole recursion; gradients are exact soft alignments
    via autodiff of the recursion (torch uses a hand-written backward).

    ``targets`` must be the padded 2-D (B, S) form (the 1-D concatenated
    form is data-dependent and unsupported under static shapes).
    ``log_probs`` is (T, B, C) and must already be log-softmaxed."""
    check(log_probs.ndim == 3,
          lambda: f"ctc_loss: log_probs must be (T,B,C), got {log_probs.ndim}-D")
    check(targets.ndim == 2,
          "ctc_loss: only the padded 2-D targets form is supported (the 1-D "
          "concatenated form has data-dependent layout; pad to (B, S))")
    check(reduction in ("none", "mean", "sum"),
          lambda: f"ctc_loss: unknown reduction {reduction!r}")
    T, B, C = log_probs.shape
    S = targets.shape[1]
    check(int(pyval(blank)) >= 0 and int(pyval(blank)) < C,
          lambda: f"ctc_loss: blank={blank} out of range for {C} classes")
    blank = int(pyval(blank))
    S2 = 2 * S + 1
    f32 = dtypes.float32
    neg_inf = ops.full((), _CTC_LOG_EPS, dtype=f32)

    # blank-extended targets ext (B, S2): [blank, t0, blank, t1, ..., blank]
    pos = ops.arange(S2)                                   # (S2,)
    tgt_idx = ops.clamp(ops.true_divide(ops.sub(pos, 1), 2), min=0)
    tgt_idx = ops.convert_element_type(tgt_idx, dtypes.int32)
    tgt_gathered = ops.gather(targets, 1,
                              ops.expand(ops.reshape(tgt_idx, (1, S2)), (B, S2)))
    is_label = ops.eq(ops.remainder(pos, 2), 1)            # (S2,) odd = label
    ext = ops.where(ops.reshape(is_label, (1, S2)), tgt_gathered,
                    ops.full((), blank, dtype=targets.dtype))

    # skip transition s-2 -> s allowed when ext[s] is a label differing from
    # ext[s-2]
    ext_m2 = ops.cat([ops.full((B, 2), blank, dtype=ext.dtype),
                      ops.narrow(ext, 1, 0, S2 - 2)], 1)
    allow_skip = ops.logical_and(ops.reshape(is_label, (1, S2)),
                                 ops.ne(ext, ext_m2))      # (B, S2)

    def emit(t):
        """log_probs[t] gathered at the extended targets: (B, S2)."""
        lp_t = ops.squeeze(ops.narrow(log_probs, 0, t, 1), 0)  # (B, C)
        return ops.gather(ops.convert_element_type(lp_t, f32), 1,
                          ops.convert_element_type(ext, dtypes.int32))

    # alpha_0: only s=0 (blank) and s=1 (first label) can start
    start_mask = ops.reshape(ops.le(pos, 1), (1, S2))
    alpha = ops.where(start_mask, emit(0), neg_inf)

    ilen = ops.convert_element_type(input_lengths, dtypes.int32)
    for t in range(1, T):
        a1 = ops.cat([ops.full((B, 1), _CTC_LOG_EPS, dtype=f32),
                      ops.narrow(alpha, 1, 0, S2 - 1)], 1)
        a2 = ops.cat([ops.full((B, 2), _CTC_LOG_EPS, dtype=f32),
                      ops.narrow(alpha, 1, 0, S2 - 2)], 1)
        a2 = ops.where(allow_skip, a2, neg_inf)
        new_alpha = ops.add(_safe_lse([alpha, a1, a2]), emit(t))
        active = ops.reshape(ops.gt(ilen, t), (B, 1))  # t < input_length
        alpha = ops.where(active, new_alpha, alpha)

    # total log-likelihood: alpha at s = 2*target_len (final blank) and
    # s = 2*target_len - 1 (final label; absent when target_len == 0)
    tlen = ops.convert_element_type(target_lengths, dtypes.int32)
    idx_blank = ops.reshape(ops.mul(tlen, 2), (B, 1))
    l_blank = ops.squeeze(ops.gather(alpha, 1, idx_blank), 1)
    idx_label = ops.clamp(ops.sub(idx_blank, 1), min=0)
    l_label = ops.squeeze(ops.gather(alpha, 1, idx_label), 1)
    l_label = ops.where(ops.gt(tlen, 0), l_label, neg_inf)
    ll = _safe_lse([l_blank, l_label])
    loss = ops.neg(ll)
    if zero_infinity:
        impossible = ops.gt(loss, -0.5 * _CTC_LOG_EPS)
        loss = ops.where(impossible, ops.full((), 0.0, dtype=f32), loss)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return ops.sum(loss, None)
    denom = ops.convert_element_type(ops.maximum(tlen, 1), f32)
    return ops.mean(ops.true_divide(loss, denom), None)

"""NN composite operations.

Each composite is a Symbol with a stable ``nn.*`` id and a prim
decomposition, so operator executors can claim it whole — the Pallas
flash-attention executor claims ``nn.scaled_dot_product_attention`` exactly
like the reference's cudnnex/sdpaex claim torch SDPA
(``thunder/executors/sdpaex.py:239``, ``cudnnex.py:425``), and the fused
cross-entropy kernel claims ``nn.cross_entropy`` (apex/triton analog).
"""

from __future__ import annotations

import math

from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.baseutils import check, canonicalize_dim
from thunder_tpu.core.proxies import TensorProxy, pyval
import thunder_tpu.ops as ops
from thunder_tpu.ops import opsymbol


@opsymbol(id="nn.embedding")
def embedding(ids, weight, padding_idx=None):
    out = prims.take(weight, ids, 0)
    return out


@opsymbol(id="nn.one_hot")
def one_hot(ids, num_classes: int):
    classes = prims.iota(num_classes, dtype=dtypes.int32, device=ids.device)
    classes = ops.expand_to(classes, ids.shape + (num_classes,))
    expanded = ops.expand_to(ops.unsqueeze(ids, -1), ids.shape + (num_classes,))
    return ops.convert_element_type(ops.eq(expanded, classes), dtypes.int32)


@opsymbol(id="nn.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    nd = len(normalized_shape)
    check(tuple(a.shape[-nd:]) == tuple(normalized_shape),
          lambda: f"layer_norm: normalized_shape {normalized_shape} != trailing dims of {a.shape}")
    dims = tuple(range(a.ndim - nd, a.ndim))
    x = ops.convert_element_type(a, dtypes.float32) if a.dtype in (dtypes.float16, dtypes.bfloat16) else a
    m = ops.mean(x, dims, keepdim=True)
    centered = ops.sub(x, m)
    v = ops.mean(ops.mul(centered, centered), dims, keepdim=True)
    out = ops.mul(centered, ops.rsqrt(ops.add(v, eps)))
    if weight is not None:
        out = ops.mul(out, weight)
    if bias is not None:
        out = ops.add(out, bias)
    return ops.convert_element_type(out, a.dtype)


@opsymbol(id="nn.rms_norm")
def rms_norm(a, weight=None, eps: float = 1e-5, dim: int = -1):
    d = canonicalize_dim(a.ndim, dim)
    x = ops.convert_element_type(a, dtypes.float32) if a.dtype in (dtypes.float16, dtypes.bfloat16) else a
    ms = ops.mean(ops.mul(x, x), d, keepdim=True)
    out = ops.mul(x, ops.rsqrt(ops.add(ms, eps)))
    out = ops.convert_element_type(out, a.dtype)
    if weight is not None:
        out = ops.mul(out, weight)
    return out


@opsymbol(id="nn.dropout")
def dropout(a, p: float = 0.5, training: bool = True):
    p = float(pyval(p))
    if not training or p == 0.0:
        return a
    check(0.0 <= p < 1.0, lambda: f"dropout p={p} out of range")
    keep = ops.bernoulli(1.0 - p, a.shape, dtype=a.dtype)
    return ops.mul(ops.mul(a, keep), 1.0 / (1.0 - p))


@opsymbol(id="nn.mse_loss")
def mse_loss(input, target, reduction: str = "mean"):
    d = ops.sub(input, target)
    sq = ops.mul(d, d)
    if reduction == "mean":
        return ops.mean(sq)
    if reduction == "sum":
        return ops.sum(sq)
    return sq


@opsymbol(id="nn.cross_entropy")
def cross_entropy(logits, target, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", label_smoothing: float = 0.0):
    """logits: (N, C) or (N, C, ...) float; target: (N, ...) int class ids."""
    check(weight is None, "cross_entropy: class weights not yet supported")
    C = logits.shape[1] if logits.ndim > 1 else logits.shape[0]
    if logits.ndim > 2:
        # (N, C, d1..) -> (N*d1.., C)
        perm = (0,) + tuple(range(2, logits.ndim)) + (1,)
        logits = ops.reshape(ops.transpose(logits, perm), (-1, C))
        target = ops.reshape(target, (-1,))
    logp = ops.log_softmax(logits, -1)
    tgt = ops.convert_element_type(target, dtypes.int32)
    safe_tgt = ops.where(ops.eq(tgt, ignore_index), ops.zeros_like(tgt), tgt)
    picked = ops.squeeze(prims.take_along_axis(logp, ops.unsqueeze(safe_tgt, -1), 1), (1,))
    nll = ops.neg(picked)
    if label_smoothing > 0.0:
        smooth = ops.neg(ops.mean(logp, -1))
        nll = ops.add(ops.mul(nll, 1.0 - label_smoothing), ops.mul(smooth, label_smoothing))
    valid = ops.ne(tgt, ignore_index)
    nll = ops.where(valid, nll, ops.zeros_like(nll))
    if reduction == "none":
        return nll
    if reduction == "sum":
        return ops.sum(nll)
    count = ops.sum(ops.convert_element_type(valid, dtypes.float32))
    return ops.true_divide(ops.sum(nll), ops.maximum(count, 1.0))


@opsymbol(id="nn.scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                                 is_causal: bool = False, scale: float | None = None):
    """q,k,v: (..., L, E) / (..., S, E). Decomposes to softmax(q k^T / sqrt(E)) v;
    the Pallas flash-attention executor claims this symbol on TPU."""
    E = q.shape[-1]
    L, S = q.shape[-2], k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(E)
    qf = ops.convert_element_type(q, dtypes.float32)
    kf = ops.convert_element_type(k, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    scores = ops.mul(ops.matmul(qf, kf.mT), scale)
    if is_causal:
        check(attn_mask is None, "cannot pass both is_causal and attn_mask")
        causal = ops.tril_mask(L, S, 0, device=q.device)
        scores = ops.where(ops.expand_to(causal, scores.shape), scores,
                           ops.full_like(scores, -float("inf")))
    if attn_mask is not None:
        if attn_mask.dtype.is_bool:
            scores = ops.where(ops.expand_to(attn_mask, scores.shape), scores,
                               ops.full_like(scores, -float("inf")))
        else:
            scores = ops.add(scores, attn_mask)
    probs = ops.softmax(scores, -1)
    if dropout_p > 0.0:
        probs = dropout(probs, dropout_p)
    out = ops.matmul(probs, vf)
    return ops.convert_element_type(out, q.dtype)

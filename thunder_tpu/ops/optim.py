"""Optimizer composite operations.

``optim.adamw_step`` is the per-parameter AdamW update chain as ONE
claimable composite (its decomposition is exactly the pointwise chain
``thunder_tpu.optim.AdamW.update`` used to inline), and
``optim.fused_adamw`` is the multi-tensor form the optimizer fusion pass
(``core/fusion_passes.optimizer_fusion_pass``) builds from dtype-bucketed
groups of those chains — the trace-level analog of the reference
ecosystem's "foreach"/multi-tensor optimizer paths (apex
``multi_tensor_apply``): one kernel launch per dtype bucket instead of one
fused pointwise chain per parameter.

Neither symbol is ever differentiated: both run on detached gradients and
optimizer state strictly after the backward, so no VJP rules exist (see
``tests/test_grad_coverage.py`` for the recorded exemption).
"""

from __future__ import annotations

from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
import thunder_tpu.ops as ops
from thunder_tpu.ops import opsymbol


@opsymbol(id="optim.adamw_step")
def adamw_step(p, g, m, v, bc1, bc2, *, lr: float = 1e-3, beta1: float = 0.9,
               beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
               state_dtype=None, v_dtype=None):
    """One parameter's AdamW update: ``(p, g, m, v, bias_corrections) ->
    (p_new, m_new, v_new)``.

    ``bc1``/``bc2`` are the traced bias-correction scalars ``1 - betaᵢ^step``
    (computed once per update and shared by every parameter, so the fusion
    pass can bucket chains that agree on them). Arithmetic is f32 (upcast,
    update, store rounded). ``state_dtype``/``v_dtype`` are the CONFIGURED
    storage dtypes for m/v (None keeps each input's own dtype): resuming
    from a checkpoint whose moments were saved wider than the optimizer is
    configured for must re-coerce on the first step, exactly as
    ``AdamW.update`` always did — not silently keep the wider state.
    """
    gf = ops.convert_element_type(g, dtypes.float32)
    mf = ops.convert_element_type(m, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    m_new = ops.add(ops.mul(mf, beta1), ops.mul(gf, 1.0 - beta1))
    v_new = ops.add(ops.mul(vf, beta2), ops.mul(ops.mul(gf, gf), 1.0 - beta2))
    m_hat = ops.true_divide(m_new, bc1)
    v_hat = ops.true_divide(v_new, bc2)
    upd = ops.true_divide(m_hat, ops.add(ops.sqrt(v_hat), eps))
    pf = ops.convert_element_type(p, dtypes.float32)
    if weight_decay:
        upd = ops.add(upd, ops.mul(pf, weight_decay))
    p_new = ops.sub(pf, ops.mul(upd, lr))
    return (ops.convert_element_type(p_new, p.dtype),
            ops.convert_element_type(m_new, state_dtype if state_dtype is not None else m.dtype),
            ops.convert_element_type(v_new, v_dtype if v_dtype is not None else v.dtype))


@opsymbol(id="optim.fused_adamw")
def fused_adamw(params, grads, ms, vs, bc1, bc2, *, lr: float = 1e-3,
                beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                weight_decay: float = 0.0, state_dtype=None, v_dtype=None):
    """Multi-tensor AdamW over one dtype bucket: applies ``adamw_step`` to
    every (p, g, m, v) quadruple and returns ``(new_params, new_ms, new_vs)``
    as parallel tuples.

    Built POST-autodiff by ``optimizer_fusion_pass`` and claimed by the
    Pallas executor as ONE flattened kernel launch per bucket
    (``executors/pallasex.py::pallas_fused_adamw``). Unclaimed, this
    decomposition is exactly the per-parameter chains, so numerics are
    identical either way.
    """
    params, grads, ms, vs = tuple(params), tuple(grads), tuple(ms), tuple(vs)
    check(len(params) > 0, "fused_adamw: empty bucket")
    check(len(params) == len(grads) == len(ms) == len(vs),
          lambda: f"fused_adamw: mismatched bucket lengths "
                  f"{(len(params), len(grads), len(ms), len(vs))}")
    triples = [adamw_step(p, g, m, v, bc1, bc2, lr=lr, beta1=beta1, beta2=beta2,
                          eps=eps, weight_decay=weight_decay,
                          state_dtype=state_dtype, v_dtype=v_dtype)
               for p, g, m, v in zip(params, grads, ms, vs)]
    return (tuple(t[0] for t in triples),
            tuple(t[1] for t in triples),
            tuple(t[2] for t in triples))

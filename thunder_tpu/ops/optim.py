"""Optimizer composite operations.

``optim.adamw_step`` is the per-parameter AdamW update chain as ONE
claimable composite (its decomposition is exactly the pointwise chain
``thunder_tpu.optim.AdamW.update`` used to inline), and
``optim.fused_adamw`` is the multi-tensor form the optimizer fusion pass
(``core/fusion_passes.optimizer_fusion_pass``) builds from dtype-bucketed
groups of those chains — the trace-level analog of the reference
ecosystem's "foreach"/multi-tensor optimizer paths (apex
``multi_tensor_apply``): one kernel launch per dtype bucket instead of one
fused pointwise chain per parameter.

Neither symbol is ever differentiated: both run on detached gradients and
optimizer state strictly after the backward, so no VJP rules exist (see
``tests/test_grad_coverage.py`` for the recorded exemption).
"""

from __future__ import annotations

import math

from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
import thunder_tpu.ops as ops
from thunder_tpu.ops import opsymbol

# Slab geometry shared by the Pallas multi-tensor kernel, the slab-persistent
# optimizer state, and checkpoint layout conversion: ONE definition, so a
# slab packed at init is bit-compatible with the slab the kernel would build
# from the same bucket (that identity is what makes slab-persistent updates
# bit-identical to the pack-per-step path).
SLAB_LANE = 128        # last-dim tile width (v5e lane count)
SLAB_ROW_BLOCK = 512   # rows per kernel grid step


def slab_geometry(total_elems: int) -> tuple[int, int]:
    """``(rows_padded, row_block)`` of the ``(rows, 128)`` slab holding
    ``total_elems`` flattened elements (zero-padded tail)."""
    rows = max(-(-total_elems // SLAB_LANE), 1)
    bn = min(SLAB_ROW_BLOCK, -(-rows // 8) * 8)
    rows_pad = -(-rows // bn) * bn
    return rows_pad, bn


@opsymbol(id="optim.adamw_step")
def adamw_step(p, g, m, v, bc1, bc2, *, lr: float = 1e-3, beta1: float = 0.9,
               beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
               state_dtype=None, v_dtype=None):
    """One parameter's AdamW update: ``(p, g, m, v, bias_corrections) ->
    (p_new, m_new, v_new)``.

    ``bc1``/``bc2`` are the traced bias-correction scalars ``1 - betaᵢ^step``
    (computed once per update and shared by every parameter, so the fusion
    pass can bucket chains that agree on them). Arithmetic is f32 (upcast,
    update, store rounded). ``state_dtype``/``v_dtype`` are the CONFIGURED
    storage dtypes for m/v (None keeps each input's own dtype): resuming
    from a checkpoint whose moments were saved wider than the optimizer is
    configured for must re-coerce on the first step, exactly as
    ``AdamW.update`` always did — not silently keep the wider state.
    """
    gf = ops.convert_element_type(g, dtypes.float32)
    mf = ops.convert_element_type(m, dtypes.float32)
    vf = ops.convert_element_type(v, dtypes.float32)
    m_new = ops.add(ops.mul(mf, beta1), ops.mul(gf, 1.0 - beta1))
    v_new = ops.add(ops.mul(vf, beta2), ops.mul(ops.mul(gf, gf), 1.0 - beta2))
    m_hat = ops.true_divide(m_new, bc1)
    v_hat = ops.true_divide(v_new, bc2)
    upd = ops.true_divide(m_hat, ops.add(ops.sqrt(v_hat), eps))
    pf = ops.convert_element_type(p, dtypes.float32)
    if weight_decay:
        upd = ops.add(upd, ops.mul(pf, weight_decay))
    p_new = ops.sub(pf, ops.mul(upd, lr))
    return (ops.convert_element_type(p_new, p.dtype),
            ops.convert_element_type(m_new, state_dtype if state_dtype is not None else m.dtype),
            ops.convert_element_type(v_new, v_dtype if v_dtype is not None else v.dtype))


@opsymbol(id="optim.fused_adamw")
def fused_adamw(params, grads, ms, vs, bc1, bc2, *, lr: float = 1e-3,
                beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                weight_decay: float = 0.0, state_dtype=None, v_dtype=None):
    """Multi-tensor AdamW over one dtype bucket: applies ``adamw_step`` to
    every (p, g, m, v) quadruple and returns ``(new_params, new_ms, new_vs)``
    as parallel tuples.

    Built POST-autodiff by ``optimizer_fusion_pass`` and claimed by the
    Pallas executor as ONE flattened kernel launch per bucket
    (``executors/pallasex.py::pallas_fused_adamw``). Unclaimed, this
    decomposition is exactly the per-parameter chains, so numerics are
    identical either way.
    """
    params, grads, ms, vs = tuple(params), tuple(grads), tuple(ms), tuple(vs)
    check(len(params) > 0, "fused_adamw: empty bucket")
    check(len(params) == len(grads) == len(ms) == len(vs),
          lambda: f"fused_adamw: mismatched bucket lengths "
                  f"{(len(params), len(grads), len(ms), len(vs))}")
    triples = [adamw_step(p, g, m, v, bc1, bc2, lr=lr, beta1=beta1, beta2=beta2,
                          eps=eps, weight_decay=weight_decay,
                          state_dtype=state_dtype, v_dtype=v_dtype)
               for p, g, m, v in zip(params, grads, ms, vs)]
    return (tuple(t[0] for t in triples),
            tuple(t[1] for t in triples),
            tuple(t[2] for t in triples))


@opsymbol(id="optim.fused_adamw_slab")
def fused_adamw_slab(params, grads, m_slab, v_slab, bc1, bc2, *,
                     sizes, lr: float = 1e-3, beta1: float = 0.9,
                     beta2: float = 0.999, eps: float = 1e-8,
                     weight_decay: float = 0.0):
    """Multi-tensor AdamW over one dtype bucket whose m/v moments LIVE in
    ``(rows, 128)`` slabs between steps (``optim.AdamW(slab_persistent=True)``):
    ``(params, grads, m_slab, v_slab, bias_corrections) ->
    (new_params, new_m_slab, new_v_slab)``.

    The Pallas claim (``executors/pallasex.py::pallas_fused_adamw_slab``)
    reads/writes the slabs directly — the m/v pack/unpack around the kernel
    (the ``pack_bytes_if_unabsorbed`` risk PERF_R6 recorded) does not exist
    on this path. Unclaimed, this decomposition unpacks each parameter's
    moment rows from the slab, runs the exact per-parameter ``adamw_step``
    chain, and repacks — numerics are identical either way. The slab's
    zero-padded tail is invariant under the update (g=0, p=0 ⇒
    m,v decay toward 0 from 0), so decomposition and kernel agree on the
    pad lanes too.
    """
    params, grads = tuple(params), tuple(grads)
    sizes = tuple(int(s) for s in sizes)
    check(len(params) > 0, "fused_adamw_slab: empty bucket")
    check(len(params) == len(grads) == len(sizes),
          lambda: f"fused_adamw_slab: mismatched bucket lengths "
                  f"{(len(params), len(grads), len(sizes))}")
    total = sum(sizes)
    rows_pad, _ = slab_geometry(total)
    check(tuple(m_slab.shape) == (rows_pad, SLAB_LANE)
          and tuple(v_slab.shape) == (rows_pad, SLAB_LANE),
          lambda: f"fused_adamw_slab: slab shape "
                  f"{tuple(m_slab.shape)}/{tuple(v_slab.shape)} does not match "
                  f"the bucket geometry ({rows_pad}, {SLAB_LANE}) for "
                  f"{total} elements")
    m_flat = ops.reshape(m_slab, (rows_pad * SLAB_LANE,))
    v_flat = ops.reshape(v_slab, (rows_pad * SLAB_LANE,))
    new_ps, new_ms, new_vs = [], [], []
    off = 0
    for p, g, n in zip(params, grads, sizes):
        m_i = ops.reshape(ops.narrow(m_flat, 0, off, n), tuple(p.shape))
        v_i = ops.reshape(ops.narrow(v_flat, 0, off, n), tuple(p.shape))
        p_new, m_new, v_new = adamw_step(
            p, g, m_i, v_i, bc1, bc2, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay,
            state_dtype=dtypes.to_dtype(m_slab.dtype),
            v_dtype=dtypes.to_dtype(v_slab.dtype))
        new_ps.append(p_new)
        new_ms.append(ops.reshape(m_new, (n,)))
        new_vs.append(ops.reshape(v_new, (n,)))
        off += n
    pad = rows_pad * SLAB_LANE - total
    if pad:
        # pad lanes stay exactly zero (they start zero and decay from zero),
        # matching what the claimed kernel computes for them
        new_ms.append(ops.full((pad,), 0.0, dtype=dtypes.to_dtype(m_slab.dtype)))
        new_vs.append(ops.full((pad,), 0.0, dtype=dtypes.to_dtype(v_slab.dtype)))
    m_out = ops.reshape(new_ms[0] if len(new_ms) == 1 else ops.cat(new_ms, 0),
                        (rows_pad, SLAB_LANE))
    v_out = ops.reshape(new_vs[0] if len(new_vs) == 1 else ops.cat(new_vs, 0),
                        (rows_pad, SLAB_LANE))
    return tuple(new_ps), m_out, v_out

"""The core operation language ("ops"): user-facing tensor operations that
decompose into prims, adding numpy/torch-style broadcasting, type promotion,
and composite ops (activations, norms, attention, losses).

Reference parity: ``thunder/clang/__init__.py`` (~124 clangops) +
``thunder/torch/__init__.py`` (torch dialect). Here both collapse into one
TPU-first namespace: ops are Symbols with stable string ids (e.g.
``"nn.scaled_dot_product_attention"``) so operator executors (Pallas kernels)
can claim them exactly like cudnnex/sdpaex claim torch SDPA in the reference.
"""

from __future__ import annotations

import math
import operator as _pyop
from numbers import Number
from typing import Any, Sequence

from thunder_tpu.core import dtypes, prims
from thunder_tpu.core.baseutils import check, canonicalize_dim, canonicalize_dims
from thunder_tpu.core.proxies import NumberProxy, Proxy, TensorProxy, pyval
from thunder_tpu.core.symbol import Symbol
from thunder_tpu.core.trace import get_tracectx

_opsym_registry: dict[str, Symbol] = {}


def constant_tensor(value):
    """Lift a concrete array (e.g. a closure-captured numpy/jax array) into
    the trace as a named constant producer (the reference bakes such values
    through its interpreter's provenance machinery; here they become explicit
    const bsyms that XLA embeds as literals)."""
    from thunder_tpu.core.devices import default_device

    trc = get_tracectx()
    check(trc is not None, "constant_tensor requires a trace context")
    idx = getattr(trc, "_const_counter", 0)
    trc._const_counter = idx + 1
    out = TensorProxy(shape=value.shape, dtype=dtypes.to_dtype(value.dtype),
                      device=default_device())
    sym = Symbol(f"const_tensor{idx}", None, id=f"const_tensor:{idx}:{id(value)}",
                 is_prim=True, python_impl=lambda _v=value: _v)
    trc.add_bound_symbol(sym.bind(output=out))
    return out


def _lift_arrays(x):
    if isinstance(x, Proxy) or isinstance(x, Number) or x is None:
        return x
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return constant_tensor(x)
    return x


def opsymbol(fn=None, *, name: str | None = None, id: str | None = None):
    """Register fn as a traceable composite Symbol with a stable id.
    (Concrete arrays in arguments are lifted to trace constants by
    ``Symbol.__call__``.)"""

    def deco(fn):
        sname = name or fn.__name__
        sym = Symbol(sname, fn, id=id or f"ops.{sname}", is_prim=False)
        _opsym_registry[sym.id] = sym
        return sym

    return deco(fn) if fn is not None else deco


def get_op(op_id: str) -> Symbol | None:
    return _opsym_registry.get(op_id)


# ---------------------------------------------------------------------------
# broadcasting / promotion helpers
# ---------------------------------------------------------------------------

def compute_broadcast_shape(*shapes) -> tuple[int, ...]:
    out: list[int] = []
    for shape in shapes:
        if shape is None:
            continue
        shape = list(shape)
        diff = len(shape) - len(out)
        if diff > 0:
            out = [1] * diff + out
        for i in range(1, len(shape) + 1):
            s = shape[-i]
            if out[-i] == 1:
                out[-i] = s
            else:
                check(s == 1 or s == out[-i],
                      lambda: f"shapes {shapes} are not broadcastable")
    return tuple(out)


def expand_to(a: TensorProxy, shape: Sequence[int]) -> TensorProxy:
    """Right-aligned broadcast of ``a`` to ``shape`` (numpy semantics)."""
    shape = tuple(shape)
    if a.shape == shape:
        return a
    offset = len(shape) - a.ndim
    check(offset >= 0, lambda: f"cannot broadcast rank {a.ndim} to {shape}")
    bdims = tuple(range(offset, len(shape)))
    return prims.broadcast_in_dim(a, shape, bdims)


def maybe_broadcast(*args):
    shapes = [a.shape for a in args if isinstance(a, TensorProxy)]
    if not shapes:
        return args
    common = compute_broadcast_shape(*shapes)
    return tuple(expand_to(a, common) if isinstance(a, TensorProxy) else a for a in args)


def _float_promote(a):
    if isinstance(a, TensorProxy) and a.dtype.is_exact:
        return prims.convert_element_type(a, dtypes.float32)
    if isinstance(a, (bool, int)):
        return float(a)
    return a


# ---------------------------------------------------------------------------
# dtype / device movement
# ---------------------------------------------------------------------------

def convert_element_type(a, dt):
    dt = dtypes.to_dtype(dt)
    if isinstance(a, TensorProxy):
        if a.dtype is dt:
            return a
        return prims.convert_element_type(a, dt)
    return a


to = convert_element_type


def device_put(a, device):
    from thunder_tpu.core.devices import to_device

    return prims.device_put(a, to_device(device))


def detach(a):
    return prims.detach(a)


stop_gradient = detach


def item(a):
    return prims.item(a)


def sharding_constraint(a, spec):
    return prims.sharding_constraint(a, tuple(spec))


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def _default_dtype_for(v) -> dtypes.dtype:
    if isinstance(v, bool):
        return dtypes.bool8
    if isinstance(v, int):
        return dtypes.int32
    if isinstance(v, complex):
        return dtypes.complex64
    return dtypes.float32


def full(shape, fill_value, *, dtype=None, device=None):
    shape = tuple(shape)
    check(all(int(s) >= 0 for s in shape),
          lambda: f"full: shape must be nonnegative, got {shape}")
    dtype = dtypes.to_dtype(dtype) if dtype is not None else _default_dtype_for(pyval(fill_value))
    return prims.full(tuple(shape), fill_value, dtype, device)


def full_like(a, fill_value, *, dtype=None, device=None):
    _tensor_like(a, "full_like")
    return full(a.shape, fill_value, dtype=dtype or a.dtype, device=device or a.device)


def zeros(*shape, dtype=None, device=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return full(shape, 0.0 if dtype is None else 0, dtype=dtype or dtypes.float32, device=device)


def ones(*shape, dtype=None, device=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return full(shape, 1.0 if dtype is None else 1, dtype=dtype or dtypes.float32, device=device)


def zeros_like(a, *, dtype=None, device=None):
    _tensor_like(a, "zeros_like")
    return full_like(a, 0, dtype=dtype, device=device)


def ones_like(a, *, dtype=None, device=None):
    _tensor_like(a, "ones_like")
    return full_like(a, 1, dtype=dtype, device=device)


def arange(start, end=None, step=1, *, dtype=None, device=None):
    if end is None:
        start, end = 0, start
    start, end, step = pyval(start), pyval(end), pyval(step)
    check(step != 0, "arange: step must be nonzero")
    if dtype is None:
        dtype = dtypes.int32 if all(isinstance(x, int) for x in (start, end, step)) else dtypes.float32
    length = max(0, math.ceil((end - start) / step))
    return prims.iota(length, start=start, step=step, dtype=dtypes.to_dtype(dtype), device=device)


def _tensor_like(a, opname: str):
    """Named trace-time type contract shared by the shape/dim ops: the
    failure mode must be a TypeError naming the op, not an AttributeError
    from ``.ndim`` somewhere downstream (reference: clang ops validate
    inputs up front, ``thunder/clang/__init__.py``)."""
    check(isinstance(a, TensorProxy) or hasattr(a, "ndim"),
          lambda: f"{opname}: expected a tensor, got {type(a).__name__}",
          exc_type=TypeError)
    return a


def _tensor_seq(tensors, opname: str):
    """Sequence-of-tensors contract shared by the stack family."""
    check(hasattr(tensors, "__iter__") and not isinstance(tensors, str),
          lambda: f"{opname}: expected a sequence of tensors, got "
                  f"{type(tensors).__name__}", exc_type=TypeError)
    return [_tensor_like(t, opname) for t in tensors]


def tril_mask(rows: int, cols: int, diagonal: int = 0, *, device=None):
    """Boolean lower-triangular mask built from iota compares (fusible)."""
    check(int(rows) >= 0 and int(cols) >= 0,
          lambda: f"tril_mask: rows/cols must be nonnegative, got {rows}, {cols}")
    r = prims.iota(rows, dtype=dtypes.int32, device=device)
    c = prims.iota(cols, dtype=dtypes.int32, device=device)
    r2 = expand_to(reshape(r, (rows, 1)), (rows, cols))
    c2 = expand_to(reshape(c, (1, cols)), (rows, cols))
    return ge(add(r2, diagonal), c2)


def tril(a, diagonal: int = 0):
    _tensor_like(a, "tril")
    mask = tril_mask(a.shape[-2], a.shape[-1], diagonal, device=a.device)
    return where(expand_to(mask, a.shape), a, zeros_like(a))


def triu(a, diagonal: int = 0):
    _tensor_like(a, "triu")
    mask = tril_mask(a.shape[-2], a.shape[-1], diagonal - 1, device=a.device)
    return where(expand_to(mask, a.shape), zeros_like(a), a)


# ---------------------------------------------------------------------------
# rng: functional key threading through the trace
# ---------------------------------------------------------------------------

def _next_rng_key() -> TensorProxy:
    """Split the trace-level RNG key and return a fresh subkey.

    The first random op creates an ``rng_key`` input proxy; the jit driver
    appends it to the trace signature and feeds a fresh key per call —
    functional replacement for the reference's GET_AND_UPDATE_RNG_STATE
    (``thunder/core/prims.py``) with reproducible, cache-friendly semantics.
    """
    trc = get_tracectx()
    check(trc is not None, "random ops require a trace context")
    key = getattr(trc, "rng_key_proxy", None)
    if key is None:
        key = TensorProxy("rng_key", shape=(2,), dtype=dtypes.uint32)
        trc.rng_input_proxy = key
    newkey, sub = prims.rng_split(key)
    trc.rng_key_proxy = newkey
    return sub


def uniform(shape, minval=0.0, maxval=1.0, *, dtype=dtypes.float32, key=None):
    key = key if key is not None else _next_rng_key()
    return prims.uniform(tuple(shape), minval, maxval, dtype=dtypes.to_dtype(dtype), key=key)


def rand(*shape, dtype=dtypes.float32, key=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return uniform(shape, 0.0, 1.0, dtype=dtype, key=key)


def randn(*shape, dtype=dtypes.float32, key=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    key = key if key is not None else _next_rng_key()
    return prims.normal(tuple(shape), dtype=dtypes.to_dtype(dtype), key=key)


def bernoulli(p, shape, *, dtype=dtypes.bool8, key=None):
    u = uniform(shape, 0.0, 1.0, dtype=dtypes.float32, key=key)
    return convert_element_type(lt(u, p), dtype)


# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------

def _make_unary(name: str, prim, *, float_promote: bool = False, py=None):
    def meta(a):
        if isinstance(a, Number):
            check(py is not None, lambda: f"{name} of a python number is unsupported")
            return py(a)
        # named trace-time contract (not a cryptic AttributeError downstream):
        # the reference's clang ops validate inputs the same way
        check(isinstance(a, (TensorProxy, NumberProxy)) or hasattr(a, "shape"),
              lambda: f"{name}: expected a tensor or number, got {type(a).__name__}",
              exc_type=TypeError)
        if float_promote:
            a = _float_promote(a)
        return prim(a)

    meta.__name__ = name
    return opsymbol(meta, name=name)


abs = _make_unary("abs", prims.abs, py=_pyop.abs)
acos = _make_unary("acos", prims.acos, float_promote=True, py=math.acos)
acosh = _make_unary("acosh", prims.acosh, float_promote=True, py=math.acosh)
asin = _make_unary("asin", prims.asin, float_promote=True, py=math.asin)
asinh = _make_unary("asinh", prims.asinh, float_promote=True, py=math.asinh)
atan = _make_unary("atan", prims.atan, float_promote=True, py=math.atan)
atanh = _make_unary("atanh", prims.atanh, float_promote=True, py=math.atanh)
bitwise_not = _make_unary("bitwise_not", prims.bitwise_not, py=_pyop.invert)
ceil = _make_unary("ceil", prims.ceil, py=math.ceil)
cos = _make_unary("cos", prims.cos, float_promote=True, py=math.cos)
cosh = _make_unary("cosh", prims.cosh, float_promote=True, py=math.cosh)
erf = _make_unary("erf", prims.erf, float_promote=True, py=math.erf)
erfc = _make_unary("erfc", prims.erfc, float_promote=True, py=math.erfc)
erfinv = _make_unary("erfinv", prims.erfinv, float_promote=True)
exp = _make_unary("exp", prims.exp, float_promote=True, py=math.exp)
exp2 = _make_unary("exp2", prims.exp2, float_promote=True, py=lambda x: 2.0 ** x)
expm1 = _make_unary("expm1", prims.expm1, float_promote=True, py=math.expm1)
floor = _make_unary("floor", prims.floor, py=math.floor)
isfinite = _make_unary("isfinite", prims.isfinite, py=math.isfinite)
isinf = _make_unary("isinf", prims.isinf, py=math.isinf)
isnan = _make_unary("isnan", prims.isnan, py=math.isnan)
lgamma = _make_unary("lgamma", prims.lgamma, float_promote=True, py=math.lgamma)
log = _make_unary("log", prims.log, float_promote=True, py=math.log)
log10 = _make_unary("log10", prims.log10, float_promote=True, py=math.log10)
log1p = _make_unary("log1p", prims.log1p, float_promote=True, py=math.log1p)
log2 = _make_unary("log2", prims.log2, float_promote=True, py=math.log2)
logical_not = _make_unary("logical_not", prims.logical_not, py=_pyop.not_)
neg = _make_unary("neg", prims.neg, py=_pyop.neg)
reciprocal = _make_unary("reciprocal", prims.reciprocal, float_promote=True, py=lambda x: 1.0 / x)
round = _make_unary("round", prims.round)
rsqrt = _make_unary("rsqrt", prims.rsqrt, float_promote=True, py=lambda x: 1.0 / math.sqrt(x))
sign = _make_unary("sign", prims.sign)
signbit = _make_unary("signbit", prims.signbit)
sin = _make_unary("sin", prims.sin, float_promote=True, py=math.sin)
sinh = _make_unary("sinh", prims.sinh, float_promote=True, py=math.sinh)
sqrt = _make_unary("sqrt", prims.sqrt, float_promote=True, py=math.sqrt)
tan = _make_unary("tan", prims.tan, float_promote=True, py=math.tan)
tanh = _make_unary("tanh", prims.tanh, float_promote=True, py=math.tanh)
trunc = _make_unary("trunc", prims.trunc, py=math.trunc)
digamma = _make_unary("digamma", prims.digamma, float_promote=True)
ndtri = _make_unary("ndtri", prims.ndtri, float_promote=True)


@opsymbol
def polygamma(n, a):
    """torch.polygamma(n, input): n-th derivative of digamma. Reference:
    thunder/torch/__init__.py polygamma."""
    check(isinstance(n, (int, NumberProxy)),
          lambda: f"polygamma: order n must be an int, got {type(n).__name__}",
          exc_type=TypeError)
    _tensor_like(a, "polygamma")
    a = _float_promote(a)
    return prims.polygamma(a, int(pyval(n)))


@opsymbol
def erfcinv(a):
    """Inverse of erfc: erfcinv(x) = erfinv(1 - x)."""
    return erfinv(sub(1.0, _float_promote(a)))


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------

def _make_binary(name: str, prim, *, py=None, float_promote: bool = False):
    def meta(a, b):
        if isinstance(a, Number) and isinstance(b, Number):
            check(py is not None, lambda: f"{name} of two python numbers is unsupported")
            return py(pyval(a), pyval(b))
        for x in (a, b):
            check(isinstance(x, (TensorProxy, NumberProxy, Number))
                  or hasattr(x, "shape"),
                  lambda: f"{name}: expected tensors or numbers, got {type(x).__name__}",
                  exc_type=TypeError)
        if float_promote:
            a, b = _float_promote(a), _float_promote(b)
        a, b = maybe_broadcast(a, b)
        return prim(a, b)

    meta.__name__ = name
    return opsymbol(meta, name=name)


add = _make_binary("add", prims.add, py=_pyop.add)
atan2 = _make_binary("atan2", prims.atan2, py=math.atan2, float_promote=True)
bitwise_and = _make_binary("bitwise_and", prims.bitwise_and, py=_pyop.and_)
bitwise_or = _make_binary("bitwise_or", prims.bitwise_or, py=_pyop.or_)
bitwise_xor = _make_binary("bitwise_xor", prims.bitwise_xor, py=_pyop.xor)
copysign = _make_binary("copysign", prims.copysign, py=math.copysign)
eq = _make_binary("eq", prims.eq, py=_pyop.eq)
fmod = _make_binary("fmod", prims.fmod, py=math.fmod)
zeta = _make_binary("zeta", prims.zeta, float_promote=True)
nextafter = _make_binary("nextafter", prims.nextafter, py=math.nextafter)
ge = _make_binary("ge", prims.ge, py=_pyop.ge)
gt = _make_binary("gt", prims.gt, py=_pyop.gt)
le = _make_binary("le", prims.le, py=_pyop.le)
lt = _make_binary("lt", prims.lt, py=_pyop.lt)
maximum = _make_binary("maximum", prims.maximum, py=max)
minimum = _make_binary("minimum", prims.minimum, py=min)
mul = _make_binary("mul", prims.mul, py=_pyop.mul)
ne = _make_binary("ne", prims.ne, py=_pyop.ne)
pow = _make_binary("pow", prims.pow, py=_pyop.pow)
remainder = _make_binary("remainder", prims.remainder, py=_pyop.mod)
sub = _make_binary("sub", prims.sub, py=_pyop.sub)
true_divide = _make_binary("true_divide", prims.div, py=_pyop.truediv, float_promote=True)
div = true_divide
shift_left = _make_binary("shift_left", prims.shift_left, py=_pyop.lshift)
shift_right = _make_binary("shift_right", prims.shift_right, py=_pyop.rshift)


@opsymbol
def floor_divide(a, b):
    if isinstance(a, Number) and isinstance(b, Number):
        return pyval(a) // pyval(b)
    a, b = maybe_broadcast(a, b)
    return prims.floor(prims.div(*maybe_broadcast(_float_promote(a), _float_promote(b)))) \
        if False else _floor_div_impl(a, b)


def _floor_div_impl(a, b):
    ts = [t for t in (a, b) if isinstance(t, TensorProxy)]
    if any(t.dtype.is_float for t in ts):
        return prims.floor(prims.div(a, b))
    # integer floor division, python semantics, EXACT: the dedicated prim
    # lowers to jnp.floor_divide (integer arithmetic all the way) — a
    # float round-trip would silently corrupt quotients past 2^24
    # (r5 code-review; the original bug true-divided to float outright)
    return prims.floor_div(a, b)


def logical_and(a, b):
    return bitwise_and(_to_bool(a), _to_bool(b))


def logical_or(a, b):
    return bitwise_or(_to_bool(a), _to_bool(b))


def _to_bool(a):
    if isinstance(a, TensorProxy) and not a.dtype.is_bool:
        return ne(a, 0)
    return a


# ---------------------------------------------------------------------------
# ternary / conditional
# ---------------------------------------------------------------------------

@opsymbol
def where(pred, a, b):
    pred, a, b = maybe_broadcast(pred, a, b)
    return prims.where(pred, a, b)


@opsymbol
def clamp(a, min=None, max=None):
    check(min is not None or max is not None,
          "clamp: at least one of min or max must be given")
    out = a
    if min is not None:
        out = maximum(out, min)
    if max is not None:
        out = minimum(out, max)
    return out


clip = clamp


@opsymbol
def masked_fill(a, mask, value):
    return where(mask, full_like(a, pyval(value)) if isinstance(value, Number) else value, a)


@opsymbol
def lerp(start, end, weight):
    return add(start, mul(sub(end, start), weight))


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

def reshape(a, shape):
    shape = tuple(shape)
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        inferred = a.numel // known if known else 0
        shape = tuple(inferred if s == -1 else s for s in shape)
    if shape == a.shape:
        return a
    return prims.reshape(a, shape)


def flatten(a, start_dim=0, end_dim=-1):
    _tensor_like(a, "flatten")
    start_dim = canonicalize_dim(a.ndim, start_dim)
    end_dim = canonicalize_dim(a.ndim, end_dim)
    merged = math.prod(a.shape[start_dim:end_dim + 1])
    return reshape(a, a.shape[:start_dim] + (merged,) + a.shape[end_dim + 1:])


def transpose(a, permutation):
    _tensor_like(a, "transpose")
    perm = canonicalize_dims(a.ndim, tuple(permutation))
    if perm == tuple(range(a.ndim)):
        return a
    return prims.transpose(a, perm)


permute = transpose


def movedim(a, src, dst):
    _tensor_like(a, "movedim")
    src = canonicalize_dims(a.ndim, src)
    dst = canonicalize_dims(a.ndim, dst)
    perm = [i for i in range(a.ndim) if i not in src]
    for d, s in sorted(zip(dst, src)):
        perm.insert(d, s)
    return transpose(a, perm)


def squeeze(a, dim=None):
    _tensor_like(a, "squeeze")
    if dim is None:
        dims = tuple(i for i, s in enumerate(a.shape) if s == 1)
    else:
        dims = canonicalize_dims(a.ndim, dim if isinstance(dim, (tuple, list)) else (dim,))
        dims = tuple(d for d in dims if a.shape[d] == 1)
    if not dims:
        return a
    return prims.squeeze(a, dims)


def unsqueeze(a, dim):
    _tensor_like(a, "unsqueeze")
    dim = canonicalize_dim(a.ndim + 1, dim)
    return reshape(a, a.shape[:dim] + (1,) + a.shape[dim:])


def expand(a, shape):
    """torch-style expand: -1 keeps the dim."""
    shape = tuple(shape)
    offset = len(shape) - a.ndim
    check(offset >= 0, lambda: f"expand to smaller rank: {a.shape} -> {shape}")
    out = []
    for i, s in enumerate(shape):
        if i < offset:
            out.append(s)
        else:
            cur = a.shape[i - offset]
            out.append(cur if s == -1 else s)
    return expand_to(a, tuple(out))


broadcast_to = expand_to


def cat(tensors, dim=0):
    tensors = list(tensors)
    if len(tensors) == 1:
        return tensors[0]
    return prims.cat(tensors, canonicalize_dim(tensors[0].ndim, dim))


concatenate = cat


def stack(tensors, dim=0):
    return cat([unsqueeze(t, dim) for t in tensors], dim)


def split(a, split_size, dim=0):
    _tensor_like(a, "split")
    dim = canonicalize_dim(a.ndim, dim)
    n = a.shape[dim]
    if isinstance(split_size, int):
        sizes = [split_size] * (n // split_size)
        if n % split_size:
            sizes.append(n % split_size)
    else:
        sizes = list(split_size)
    outs, off = [], 0
    for s in sizes:
        starts = [0] * a.ndim
        ends = list(a.shape)
        starts[dim], ends[dim] = off, off + s
        outs.append(prims.slice_prim(a, starts, ends))
        off += s
    return tuple(outs)


def chunk(a, chunks, dim=0):
    _tensor_like(a, "chunk")
    dim_ = canonicalize_dim(a.ndim, dim)
    n = a.shape[dim_]
    size = -(-n // chunks)
    return split(a, size, dim)


def flip(a, dims):
    _tensor_like(a, "flip")
    return prims.flip(a, canonicalize_dims(a.ndim, tuple(dims) if isinstance(dims, (tuple, list)) else (dims,)))


def pad(a, padding_config, value=0):
    """lax-style padding config: ((lo, hi, interior), ...) per dim."""
    _tensor_like(a, "pad")
    return prims.pad(a, value, tuple(padding_config))


def pad_last(a, pads: Sequence[int], value=0):
    """torch.nn.functional.pad semantics: pairs from the last dim backwards."""
    _tensor_like(a, "pad_last")
    cfg = [(0, 0, 0)] * a.ndim
    pairs = [(pads[i], pads[i + 1]) for i in range(0, len(pads), 2)]
    for i, (lo, hi) in enumerate(pairs):
        cfg[a.ndim - 1 - i] = (lo, hi, 0)
    return prims.pad(a, value, tuple(cfg))


def take(a, indices, dim=0):
    _tensor_like(a, "take")
    return prims.take(a, indices, canonicalize_dim(a.ndim, dim))


index_select = take


def gather(a, dim, index):
    _tensor_like(a, "gather")
    return prims.take_along_axis(a, index, canonicalize_dim(a.ndim, dim))


def take_along_axis(a, idx, dim):
    _tensor_like(a, "take_along_axis")
    return prims.take_along_axis(a, idx, canonicalize_dim(a.ndim, dim))


def scatter_add(a, dim, index, src):
    _tensor_like(a, "scatter_add")
    return prims.scatter_add(a, index, src, canonicalize_dim(a.ndim, dim))


def scatter(a, dim, index, src):
    """torch.scatter (replace semantics). ``src`` may be a python scalar
    (torch's ``value`` variant)."""
    _tensor_like(a, "scatter")
    d = canonicalize_dim(a.ndim, dim)
    if isinstance(src, Number):
        src = full(index.shape, src, dtype=a.dtype, device=a.device)
    return prims.scatter(a, index, src, d)


def index_copy(a, dim, index, src):
    """torch.index_copy: rank-1 ``index`` selects slices of ``a`` along
    ``dim`` to be replaced by ``src``'s slices. Lowered to the SCATTER prim
    with the index broadcast along the slice dims."""
    _tensor_like(a, "index_copy")
    d = canonicalize_dim(a.ndim, dim)
    shape = [1] * a.ndim
    shape[d] = int(index.shape[0])
    idx = broadcast_to(reshape(index, tuple(shape)), src.shape)
    return prims.scatter(a, idx, src, d)


def index_add(a, dim, index, src, *, alpha=1):
    """torch.index_add: row-wise scatter-add (1 index per slice) — lowers to
    the INDEX_ADD prim, XLA's update_window_dims fast path."""
    _tensor_like(a, "index_add")
    d = canonicalize_dim(a.ndim, dim)
    if not (isinstance(alpha, Number) and pyval(alpha) == 1):
        src = mul(src, alpha)
    return prims.index_add(a, index, src, d)


def setitem(a, idx, val):
    """Functional ``a[idx] = val``: returns the updated tensor. The torch
    dialect's ``TorchProxy.__setitem__`` rebinds through this
    (functionalization — no COPY_ ever traced, reference
    ``functionalize_inplace_ops``). Supports basic indexing (ints, slices
    with any positive step, Ellipsis), integer-tensor advanced indexing
    mixed with basic indices (``a[i, 2:5] = v``), and whole-tensor boolean
    masks (``a[mask] = scalar``). Reference parity:
    /root/reference/thunder/clang/__init__.py:381 (advanced indexing) —
    lowered TPU-first (one XLA scatter / gather+select, no index loops)."""
    _tensor_like(a, "setitem")
    if not isinstance(idx, tuple):
        idx = (idx,)
    idx = tuple(_lift_arrays(i) if _is_arraylike_idx(i) else i for i in idx)

    # boolean-mask assignment: a[mask] = v. v must be a scalar (or numel-1
    # tensor) — a (nnz,)-shaped value is a data-dependent shape XLA cannot
    # compile. Lowered to ONE select, no scatter.
    if (len(idx) == 1 and isinstance(idx[0], TensorProxy)
            and idx[0].dtype is dtypes.bool8):
        mask = idx[0]
        check(mask.ndim <= a.ndim
              and all(int(m) == int(s) for m, s in zip(mask.shape, a.shape)),
              lambda: f"setitem: boolean mask shape {tuple(mask.shape)} must "
                      f"match the leading dims of {tuple(a.shape)}", IndexError)
        val = _lift_arrays(val) if _is_arraylike_idx(val) else val
        if isinstance(val, TensorProxy):
            numel = 1
            for s in val.shape:
                numel *= int(s)
            check(numel == 1,
                  "setitem: boolean-mask assignment takes a scalar value (a "
                  "per-position value would have a data-dependent (nnz,) shape "
                  "XLA cannot compile); use ops.where for full-shape selects",
                  NotImplementedError)
            val = reshape(val, ())
        m = mask
        for _ in range(a.ndim - mask.ndim):
            m = unsqueeze(m, m.ndim)
        return where(m, convert_element_type(val, a.dtype), a)

    if any(isinstance(i, TensorProxy) for i in idx):
        check(all(i.dtype is not dtypes.bool8 for i in idx
                  if isinstance(i, TensorProxy)),
              "setitem: a boolean mask must be the sole index",
              NotImplementedError)
        return _setitem_advanced(a, idx, val)
    # expand Ellipsis
    n_spec = len([i for i in idx if i is not Ellipsis])
    idx = tuple(
        j for i in idx
        for j in ((slice(None),) * (a.ndim - n_spec) if i is Ellipsis else (i,)))
    idx = idx + (slice(None),) * (a.ndim - len(idx))
    check(len(idx) == a.ndim, lambda: f"setitem: too many indices for rank {a.ndim}")

    starts, sizes, steps, keep_dim = [], [], [], []
    for d, i in enumerate(idx):
        n = int(a.shape[d])
        if isinstance(i, int):
            check(n > 0 and -n <= i < n,
                  lambda: f"setitem: index {i} out of range for dim {d} (size {n})",
                  IndexError)
            ii = i % n
            starts.append(ii)
            sizes.append(1)
            steps.append(1)
            keep_dim.append(False)
        elif isinstance(i, slice):
            s0, e0, st = i.indices(n)
            check(st > 0, "setitem: negative slice steps are not supported; "
                  "use flip()", NotImplementedError)
            starts.append(s0)
            sizes.append(max((e0 - s0 + st - 1) // st, 0) if st > 1
                         else max(e0 - s0, 0))
            steps.append(st)
            keep_dim.append(True)
        else:
            check(False, lambda: f"setitem: unsupported index {i!r}", NotImplementedError)

    if any(s == 0 for s in sizes):
        return a  # empty region: nothing to write

    region_shape = tuple(sizes)
    if isinstance(val, TensorProxy):
        # align val to the region: insert the dims ints dropped
        v = val
        for d, kd in enumerate(keep_dim):
            if not kd and v.ndim < len(region_shape):
                v = unsqueeze(v, min(d, v.ndim))
        if v.ndim < len(region_shape):  # sub-rank values right-align
            v = reshape(v, (1,) * (len(region_shape) - v.ndim) + tuple(v.shape))
        v = broadcast_to(v, region_shape) if tuple(v.shape) != region_shape else v
    else:
        v = full(region_shape, val, dtype=a.dtype)
    v = convert_element_type(v, a.dtype)
    if all(st == 1 for st in steps):
        return prims.dynamic_update_slice(a, v, tuple(starts))

    # stepped write = gather + select (TPU-first: no scatter): expand v to
    # the full shape via per-dim takes (ve[i] = v[(i-start)//step], clamped),
    # mask the strided positions, select. All static 1-D index/mask vectors.
    import numpy as np

    ve = v
    mask = None
    for d, (s0, st, sz) in enumerate(zip(starts, steps, sizes)):
        n = int(a.shape[d])
        if s0 == 0 and st == 1 and sz == n:
            continue
        pos = np.arange(n)
        md = (pos >= s0) & (pos < s0 + sz * st) & ((pos - s0) % st == 0)
        mp = np.clip((pos - s0) // st, 0, sz - 1).astype(np.int32)
        ve = take(ve, _lift_arrays(mp), d)
        m = reshape(_lift_arrays(md), (1,) * d + (n,) + (1,) * (a.ndim - d - 1))
        mask = m if mask is None else logical_and(mask, m)
    return where(mask, ve, a) if mask is not None else ve


def _setitem_advanced(a, idx, val):
    """Advanced (integer-tensor) assignment, numpy/torch semantics:
    ``a[t0, 2:5, t1] = v``. Ints count as 0-d advanced indices; slices (any
    positive step) contribute orthogonal grid axes; non-adjacent advanced
    indices put the broadcast dims at the front (numpy rule, via a
    transpose round-trip). TPU-first lowering: build the full open index
    grid and write with ONE index_put (a single XLA scatter)."""
    import numpy as np

    check(not any(x is None for x in idx),
          "setitem: newaxis (None) cannot appear in an assignment index",
          NotImplementedError)
    n_spec = len([i for i in idx if i is not Ellipsis])
    ell = [i for i, x in enumerate(idx) if x is Ellipsis]
    if ell:
        pos = ell[0]
        idx = idx[:pos] + (slice(None),) * (a.ndim - n_spec) + idx[pos + 1:]
    else:
        idx = idx + (slice(None),) * (a.ndim - n_spec)
    check(len(idx) == a.ndim, lambda: f"setitem: too many indices for rank {a.ndim}")

    adv = [i for i, x in enumerate(idx) if not isinstance(x, slice)]
    if adv != list(range(adv[0], adv[0] + len(adv))):
        # numpy rule: separated advanced indices move their broadcast dims
        # to the FRONT — transpose them adjacent, assign, transpose back
        perm = adv + [i for i in range(a.ndim) if i not in adv]
        inv = [0] * a.ndim
        for out_pos, src in enumerate(perm):
            inv[src] = out_pos
        out = _setitem_advanced(transpose(a, tuple(perm)),
                                tuple(idx[p] for p in perm), val)
        return transpose(out, tuple(inv))

    p0 = adv[0]
    if p0 == 0 and all(isinstance(idx[d], slice) and idx[d] == slice(None)
                       for d in range(len(adv), a.ndim)):
        # leading advanced indices, trailing full slices: direct index_put
        # (XLA row scatter with update_window_dims — no grid needed)
        lead = tuple(convert_element_type(idx[d], dtypes.int32)
                     if isinstance(idx[d], TensorProxy) else idx[d]
                     for d in adv)
        return index_put(a, lead, convert_element_type(val, a.dtype)
                         if isinstance(val, TensorProxy) else val,
                         accumulate=False)
    bshape = ()
    for i in adv:
        x = idx[i]
        bshape = compute_broadcast_shape(
            bshape, tuple(x.shape) if isinstance(x, TensorProxy) else ())
    nb = len(bshape)

    # region layout: slice extents before the block, the joint broadcast
    # dims, slice extents after
    slice_meta = {}  # source dim -> (region_axis, np.arange index vector)
    region_shape = []
    axis = 0
    for d in range(p0):
        s0, e0, st = idx[d].indices(int(a.shape[d]))
        check(st > 0, "setitem: negative slice steps are not supported; use flip()",
              NotImplementedError)
        vec = np.arange(s0, e0, st, dtype=np.int32)
        slice_meta[d] = (axis, vec)
        region_shape.append(len(vec))
        axis += 1
    block_axes = (axis, axis + nb)
    region_shape.extend(bshape)
    axis += nb
    for d in range(adv[-1] + 1, a.ndim):
        s0, e0, st = idx[d].indices(int(a.shape[d]))
        check(st > 0, "setitem: negative slice steps are not supported; use flip()",
              NotImplementedError)
        vec = np.arange(s0, e0, st, dtype=np.int32)
        slice_meta[d] = (axis, vec)
        region_shape.append(len(vec))
        axis += 1
    region_shape = tuple(region_shape)
    R = len(region_shape)
    if any(s == 0 for s in region_shape):
        return a  # empty region: nothing to write

    grid = []
    for d in range(a.ndim):
        n = int(a.shape[d])
        if d in slice_meta:
            ax, vec = slice_meta[d]
            t = reshape(_lift_arrays(vec), (1,) * ax + (len(vec),) + (1,) * (R - ax - 1))
        else:
            x = idx[d]
            if isinstance(x, TensorProxy):
                x = convert_element_type(x, dtypes.int32)
                x = where(lt(x, 0), add(x, n), x)
                x = broadcast_to(x, bshape) if tuple(x.shape) != bshape else x
            else:
                check(-n <= int(x) < n,
                      lambda: f"setitem: index {x} out of range for dim {d} (size {n})",
                      IndexError)
                x = _lift_arrays(np.full(bshape, int(x) % n, dtype=np.int32))
            t = reshape(x, (1,) * block_axes[0] + bshape
                        + (1,) * (R - block_axes[1]))
        grid.append(t)

    if isinstance(val, TensorProxy):
        v = val
        if v.ndim < R:
            v = reshape(v, (1,) * (R - v.ndim) + tuple(v.shape))
        v = broadcast_to(v, region_shape) if tuple(v.shape) != region_shape else v
    else:
        v = full(region_shape, val, dtype=a.dtype)
    v = convert_element_type(v, a.dtype)
    return index_put(a, tuple(grid), v, accumulate=False)


def _is_arraylike_idx(i):
    return (not isinstance(i, (int, slice, type(Ellipsis), type(None)))
            and hasattr(i, "shape") and hasattr(i, "dtype"))


def index_put(a, indices, values, accumulate=False):
    return prims.index_put(a, tuple(indices), values, bool(accumulate))


def linearize_indices(indices, sizes, bshape):
    """Row-major linearization of jointly-broadcast integer indices over
    dims of the given ``sizes``: returns the (broadcast to ``bshape``)
    linear-index value, or a python int when every index is an int.
    Negatives are normalized; the arithmetic runs in int32 (narrow dtypes
    would overflow the stride multiply), guarded against extents past
    2**31. Shared by the advanced-indexing gather (`_getitem_multi_tensor`)
    and the index_put VJP's grad gather — one implementation, one contract."""
    flat_len = 1
    for s in sizes:
        flat_len *= s
    check(flat_len < 2 ** 31, lambda: f"indexed extent {flat_len} overflows int32 "
          "linearization", NotImplementedError)
    strides = []
    stride_acc = 1
    for s in reversed(sizes):
        strides.append(stride_acc)
        stride_acc *= s
    strides = list(reversed(strides))
    linear = None
    for t, s, st in zip(indices, sizes, strides):
        if isinstance(t, TensorProxy):
            t = convert_element_type(t, dtypes.int32)
            # normalize negatives only; out-of-range indices fall through to
            # XLA's clamp semantics like the single-tensor take path (ADVICE
            # r1: remainder() silently wrapped OOB indices)
            t = broadcast_to(where(lt(t, 0), add(t, s), t), bshape)
            term = mul(t, st) if st != 1 else t
        else:
            term = (int(t) % s) * st
        if linear is None:
            linear = term
        elif isinstance(linear, int) and isinstance(term, int):
            linear = linear + term
        else:
            linear = add(linear, term)
    return linear


def _getitem_multi_tensor(a, idx, tensor_positions):
    """Multi-tensor advanced indexing, torch/numpy semantics for a
    CONTIGUOUS block of index tensors (``a[i, j]``, ``a[:, i, j]``): the
    index tensors broadcast together, their joint result dims replace the
    indexed dims in place. TPU-first lowering: linearize the broadcast
    indices over the indexed dims' row-major strides, flatten those dims of
    ``a``, and gather with ONE take — a single XLA gather, no scatter loops.
    Entries before/after the block must be full slices (apply other basic
    indexing in a separate step)."""
    p0, pk = tensor_positions[0], tensor_positions[-1]
    check(tensor_positions == list(range(p0, pk + 1)),
          "advanced indexing tensors must be contiguous (split non-adjacent "
          "tensor indices into separate getitem steps)", NotImplementedError)
    check(all(isinstance(x, slice) and x == slice(None)
              for i, x in enumerate(idx) if i not in tensor_positions),
          "mixing tensor indices with other non-trivial indices is "
          "unsupported — apply slices/ints in a separate getitem step",
          NotImplementedError)
    tensors = [idx[i] for i in tensor_positions]
    sizes = [int(a.shape[i]) for i in tensor_positions]
    bshape = tensors[0].shape
    for t in tensors[1:]:
        bshape = compute_broadcast_shape(bshape, t.shape)
    flat_len = 1
    for s in sizes:
        flat_len *= s
    linear = linearize_indices(tensors, sizes, bshape)
    pre = tuple(int(s) for s in a.shape[:p0])
    post = tuple(int(s) for s in a.shape[pk + 1:])
    flat = reshape(a, pre + (flat_len,) + post)
    nb = len(bshape)
    lin_flat = reshape(linear, (-1,)) if nb != 1 else linear
    out = take(flat, lin_flat, len(pre))
    return reshape(out, pre + tuple(bshape) + post) if nb != 1 else out


def getitem(a, idx):
    """Basic indexing (ints, slices, None, Ellipsis) + integer-tensor
    advanced indexing (single tensor anywhere; multiple contiguous tensors
    broadcast jointly). Decomposes to slice/squeeze/take prims."""
    _tensor_like(a, "getitem")
    if not isinstance(idx, tuple):
        idx = (idx,)
    # concrete index arrays (np/jax constants) become trace constants
    idx = tuple(_lift_arrays(x) if not isinstance(x, (slice, type(Ellipsis)))
                else x for x in idx)
    # expand Ellipsis (identity checks only: `in`/`==` would trace through
    # TensorProxy.__eq__ when idx holds an advanced-indexing tensor)
    n_specified = len([i for i in idx if i is not None and i is not Ellipsis])
    ell = [i for i, x in enumerate(idx) if x is Ellipsis]
    if ell:
        pos = ell[0]
        fill = a.ndim - n_specified
        idx = idx[:pos] + (slice(None),) * fill + idx[pos + 1:]
    else:
        idx = idx + (slice(None),) * (a.ndim - n_specified)

    # advanced indexing with integer tensor(s)
    tensor_positions = [i for i, x in enumerate(idx) if isinstance(x, TensorProxy)]
    if tensor_positions:
        for i in tensor_positions:
            check(idx[i].dtype is not dtypes.bool8,
                  "boolean-mask indexing produces a data-dependent shape, which XLA "
                  "cannot compile; rewrite with ops.where / masked_fill, or multiply "
                  "by the mask", NotImplementedError)
        if len(tensor_positions) > 1:
            check(not any(x is None for x in idx),
                  "newaxis (None) cannot be mixed with multi-tensor advanced "
                  "indexing", NotImplementedError)
            import numpy as np

            # numpy semantics: ints count as 0-d advanced indices (they join
            # the broadcast block); slices (any positive step) are basic and
            # pre-applied in a separate step, which cannot shift positions
            adv = [i for i, x in enumerate(idx)
                   if isinstance(x, (TensorProxy, int, NumberProxy))]
            basic = tuple(slice(None) if i in adv else x
                          for i, x in enumerate(idx))
            out = a
            if any(not (isinstance(x, slice) and x == slice(None))
                   for x in basic):
                out = getitem(a, basic)
            idx2 = [idx[i] if i in adv else slice(None)
                    for i in range(len(idx))]
            for i in adv:
                if isinstance(idx2[i], (int, NumberProxy)):
                    n = int(out.shape[i])
                    v = int(pyval(idx2[i]))
                    check(n > 0 and -n <= v < n,
                          lambda: f"index {v} out of range for dim {i} (size {n})",
                          IndexError)
                    idx2[i] = _lift_arrays(np.asarray(v % n, dtype=np.int32))
            if adv != list(range(adv[0], adv[0] + len(adv))):
                # numpy rule: separated advanced indices put the broadcast
                # dims at the FRONT — transpose them adjacent first
                perm = adv + [i for i in range(out.ndim) if i not in adv]
                out = transpose(out, tuple(perm))
                idx2 = [idx2[p] for p in perm]
                adv = list(range(len(adv)))
            return _getitem_multi_tensor(out, tuple(idx2), adv)
        tp = tensor_positions[0]
        # the take dim is in OUT's coordinates: ints before tp are squeezed
        # away by the recursive getitem, Nones insert axes
        dim = len([x for x in idx[:tp] if isinstance(x, slice) or x is None])
        rest = list(idx)
        t = rest[tp]
        rest[tp] = slice(None)
        nontrivial = any(not (isinstance(x, slice) and x == slice(None)) for x in rest)
        out = getitem(a, tuple(rest)) if nontrivial else a
        return take(out, t, dim)

    starts, ends, strides = [], [], []
    squeeze_dims, unsqueeze_positions = [], []
    dim = 0
    out_dim = 0
    for x in idx:
        if x is None:
            unsqueeze_positions.append(out_dim)
            out_dim += 1
            continue
        size = a.shape[dim]
        if isinstance(x, (int, NumberProxy)):
            x = int(pyval(x))
            x = x + size if x < 0 else x
            check(0 <= x < size, lambda: f"index {x} out of range for dim {dim} (size {size})", IndexError)
            starts.append(x); ends.append(x + 1); strides.append(1)
            squeeze_dims.append(dim)
        elif isinstance(x, slice):
            start, stop, step = x.indices(size)
            check(step > 0, "negative slice steps are not supported; use flip()")
            starts.append(start); ends.append(max(start, stop)); strides.append(step)
            out_dim += 1
        else:
            raise TypeError(f"unsupported index {x!r}")
        dim += 1

    out = a
    if any(s != 0 for s in starts) or any(e != s for e, s in zip(ends, a.shape)) or any(st != 1 for st in strides):
        out = prims.slice_prim(a, starts, ends, strides)
    if squeeze_dims:
        out = prims.squeeze(out, tuple(squeeze_dims))
    for p in unsqueeze_positions:
        out = unsqueeze(out, p)
    return out


def roll(a, shifts, dims):
    _tensor_like(a, "roll")
    shifts = (shifts,) if isinstance(shifts, int) else tuple(shifts)
    dims = (dims,) if isinstance(dims, int) else tuple(dims)
    out = a
    for sh, d in zip(shifts, dims):
        d = canonicalize_dim(a.ndim, d)
        size = out.shape[d]
        sh = sh % size
        if sh == 0:
            continue
        left = getitem(out, tuple([slice(None)] * d + [slice(size - sh, size)]))
        right = getitem(out, tuple([slice(None)] * d + [slice(0, size - sh)]))
        out = cat([left, right], d)
    return out


def repeat_interleave_dim0(a, repeats: int):
    return reshape(expand_to(unsqueeze(a, 1), (a.shape[0], repeats) + a.shape[1:]),
                   (a.shape[0] * repeats,) + a.shape[1:])


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce_dims(a, dim) -> tuple[int, ...]:
    check(isinstance(a, TensorProxy) or hasattr(a, "ndim"),
          lambda: f"reduction: expected a tensor, got {type(a).__name__}",
          exc_type=TypeError)
    if dim is None:
        return tuple(range(a.ndim))
    return canonicalize_dims(a.ndim, dim if isinstance(dim, (tuple, list)) else (dim,))


def _restore_keepdim(out, a, dims):
    shape = tuple(1 if i in dims else s for i, s in enumerate(a.shape))
    return reshape(out, shape)


def _make_reduction_op(name, prim, *, promote_int_to=None):
    def meta(a, dim=None, keepdim=False, dtype=None):
        dims = _reduce_dims(a, dim)
        if dtype is not None:
            a = convert_element_type(a, dtype)
        elif promote_int_to is not None and a.dtype.is_exact and not a.dtype.is_bool:
            pass  # sum of ints stays int (torch promotes to int64; we keep int32 TPU-first)
        out = prim(a, dims)
        if keepdim:
            out = _restore_keepdim(out, a, dims)
        return out

    meta.__name__ = name
    return opsymbol(meta, name=name)


sum = _make_reduction_op("sum", prims.sum)
prod = _make_reduction_op("prod", prims.prod)
amax = _make_reduction_op("amax", prims.amax)
amin = _make_reduction_op("amin", prims.amin)


@opsymbol
def mean(a, dim=None, keepdim=False, dtype=None):
    dims = _reduce_dims(a, dim)
    n = math.prod(a.shape[d] for d in dims)
    if dtype is not None:
        a = convert_element_type(a, dtype)
    elif a.dtype.is_exact:
        a = convert_element_type(a, dtypes.float32)
    out = prims.sum(a, dims)
    out = true_divide(out, n)
    if keepdim:
        out = _restore_keepdim(out, a, dims)
    return out


@opsymbol
def var_mean(a, dim=None, correction=1, keepdim=False):
    dims = _reduce_dims(a, dim)
    n = math.prod(a.shape[d] for d in dims)
    if a.dtype.is_exact:
        a = convert_element_type(a, dtypes.float32)
    m = mean(a, dim, keepdim=True)
    centered = sub(a, m)
    v = true_divide(prims.sum(prims.mul(centered, centered), dims), builtins_max(n - correction, 1))
    if keepdim:
        v = _restore_keepdim(v, a, dims)
        return v, m
    return v, squeeze(m, dims)


def builtins_max(*args):
    import builtins

    return builtins.max(*args)


@opsymbol
def var(a, dim=None, correction=1, keepdim=False):
    v, _ = var_mean(a, dim, correction=correction, keepdim=keepdim)
    return v


@opsymbol
def std(a, dim=None, correction=1, keepdim=False):
    return sqrt(var(a, dim, correction=correction, keepdim=keepdim))


@opsymbol
def argmax(a, dim=None, keepdim=False):
    out = prims.argmax(a, dim if dim is None else canonicalize_dim(a.ndim, dim))
    if keepdim and dim is not None:
        out = _restore_keepdim(out, a, (canonicalize_dim(a.ndim, dim),))
    return out


@opsymbol
def argmin(a, dim=None, keepdim=False):
    out = prims.argmin(a, dim if dim is None else canonicalize_dim(a.ndim, dim))
    if keepdim and dim is not None:
        out = _restore_keepdim(out, a, (canonicalize_dim(a.ndim, dim),))
    return out


@opsymbol
def max_with_indices(a, dim, keepdim=False):
    _tensor_like(a, "max_with_indices")
    d = canonicalize_dim(a.ndim, dim)
    values = amax(a, dim, keepdim=keepdim)
    indices = argmax(a, dim, keepdim=keepdim)
    return values, indices


@opsymbol
def min_with_indices(a, dim, keepdim=False):
    _tensor_like(a, "min_with_indices")
    d = canonicalize_dim(a.ndim, dim)
    values = amin(a, dim, keepdim=keepdim)
    indices = argmin(a, dim, keepdim=keepdim)
    return values, indices


def all_(a, dim=None, keepdim=False):
    b = _to_bool(a)
    return convert_element_type(amin(convert_element_type(b, dtypes.uint8), dim, keepdim=keepdim), dtypes.bool8)


def any_(a, dim=None, keepdim=False):
    b = _to_bool(a)
    return convert_element_type(amax(convert_element_type(b, dtypes.uint8), dim, keepdim=keepdim), dtypes.bool8)


def cumsum(a, dim):
    _tensor_like(a, "cumsum")
    return prims.cumsum(a, canonicalize_dim(a.ndim, dim))


def cumprod(a, dim):
    _tensor_like(a, "cumprod")
    return prims.cumprod(a, canonicalize_dim(a.ndim, dim))


def sort(a, dim=-1, descending=False):
    _tensor_like(a, "sort")
    d = canonicalize_dim(a.ndim, dim)
    return prims.sort(a, d, descending), prims.argsort(a, d, descending)


def argsort(a, dim=-1, descending=False):
    _tensor_like(a, "argsort")
    return prims.argsort(a, canonicalize_dim(a.ndim, dim), descending)


def topk(a, k, dim=-1):
    d = canonicalize_dim(a.ndim, dim)
    k = int(pyval(k))
    check(0 <= k <= a.shape[d],
          lambda: f"topk: k={k} out of range for dim {d} of size {a.shape[d]}")
    return prims.topk(a, k, d)


# ---------------------------------------------------------------------------
# autocast: downcast matmul-class op inputs inside the context
# (reference: per-op autocast rules, thunder/core/transforms.py:3757-3960)
# ---------------------------------------------------------------------------

_autocast_stack: list = []


class autocast:
    """Context manager used *inside traced code*: matmul/linear/conv/SDPA
    inputs in float32 are downcast to the target dtype while active."""

    def __init__(self, dtype=dtypes.bfloat16):
        self.dtype = dtypes.to_dtype(dtype)

    def __enter__(self):
        _autocast_stack.append(self.dtype)
        return self

    def __exit__(self, *exc):
        _autocast_stack.pop()
        return False


def _autocast_dtype():
    return _autocast_stack[-1] if _autocast_stack else None


def maybe_autocast(*ts):
    dt = _autocast_dtype()
    if dt is None:
        return ts
    return tuple(
        convert_element_type(t, dt)
        if isinstance(t, TensorProxy) and t.dtype is dtypes.float32 else t
        for t in ts)


# ---------------------------------------------------------------------------
# linalg — everything decomposes into dot_general (the MXU prim)
# ---------------------------------------------------------------------------

@opsymbol
def matmul(a, b):
    a, b = maybe_autocast(a, b)
    check(isinstance(a, TensorProxy) and isinstance(b, TensorProxy), "matmul expects tensors")
    if a.ndim == 1 and b.ndim == 1:
        return prims.dot_general(a, b, contract_dims=((0,), (0,)))
    if a.ndim == 1:
        return squeeze(matmul(unsqueeze(a, 0), b), -2)
    if b.ndim == 1:
        return squeeze(matmul(a, unsqueeze(b, 1)), -1)
    if a.ndim == 2 and b.ndim == 2:
        return prims.dot_general(a, b, contract_dims=((1,), (0,)))
    # batched: broadcast batch dims
    batch = compute_broadcast_shape(a.shape[:-2], b.shape[:-2])
    a = expand_to(a, batch + a.shape[-2:])
    b = expand_to(b, batch + b.shape[-2:])
    nb = len(batch)
    return prims.dot_general(
        a, b,
        contract_dims=((nb + 1,), (nb,)),
        batch_dims=(tuple(range(nb)), tuple(range(nb))),
    )


@opsymbol(id="nn.linear")
def linear(a, w, bias=None):
    """y = a @ w.T (+ bias); w: (out_features, in_features) — torch layout.

    Tensor-parallel aware: a COLUMN_WISE weight (out-features sharded) wraps
    the input in synchronize_tp_input (identity fwd / all-reduce bwd), a
    ROW_WISE weight (in-features sharded) all-reduces the partial output —
    the reference's column/row parallel boundary comms
    (``thunder/distributed/tensor_parallel/column_wise.py:154``,
    ``row_wise.py:159``) realized at the op level.
    """
    from thunder_tpu.core.proxies import DistParallelType
    from thunder_tpu.fp8 import current_fp8

    fp8_ctx = current_fp8()
    if (fp8_ctx is not None and fp8_ctx.eligible(a, w)
            and getattr(w, "distparallel_type", DistParallelType.NONE) is DistParallelType.NONE):
        return fp8_ctx.linear(a, w, bias)
    a, w, bias = maybe_autocast(a, w, bias)
    dpt = getattr(w, "distparallel_type", DistParallelType.NONE)
    if dpt is DistParallelType.COLUMN_WISE:
        from thunder_tpu.distributed import prims as dist_prims

        a = dist_prims.synchronize_tp_input(a, w.dist_axis, w.dist_size)
    out = prims.dot_general(a, w, contract_dims=((a.ndim - 1,), (1,)))
    if dpt is DistParallelType.ROW_WISE:
        from thunder_tpu.distributed import prims as dist_prims

        out = dist_prims.synchronize_tp_output(out, w.dist_axis, w.dist_size)
    if bias is not None:
        out = add(out, bias)
    return out


@opsymbol
def outer(a, b):
    return mul(unsqueeze(a, 1), unsqueeze(b, 0))


def einsum(equation, *operands):
    check(isinstance(equation, str),
          lambda: f"einsum: first argument must be the equation string, got "
                  f"{type(equation).__name__}", exc_type=TypeError)
    check(operands and all(not isinstance(o, str) for o in operands),
          "einsum: expected tensor operands after the equation",
          exc_type=TypeError)
    operands = tuple(maybe_autocast(*operands))
    return prims.einsum(equation, *operands)


def dot_general(a, b, contract_dims, batch_dims=((), ()), preferred_element_type=None):
    return prims.dot_general(a, b, contract_dims=contract_dims, batch_dims=batch_dims,
                             preferred_element_type=preferred_element_type)


@opsymbol
def conv2d(a, w, bias=None, stride=1, padding=0, dilation=1, groups=1):
    _tensor_like(a, "conv2d")
    a, w, bias = maybe_autocast(a, w, bias)

    def _pair(x):
        return (x, x) if isinstance(x, int) else tuple(x)

    s, d = _pair(stride), _pair(dilation)
    p = _pair(padding)
    pad_cfg = tuple((pi, pi) for pi in p)
    return prims.convolution(a, w, bias, stride=s, padding=pad_cfg, dilation=d, groups=groups)


@opsymbol
def conv1d(a, w, bias=None, stride=1, padding=0, dilation=1, groups=1):
    _tensor_like(a, "conv1d")
    s = (stride,) if isinstance(stride, int) else tuple(stride)
    d = (dilation,) if isinstance(dilation, int) else tuple(dilation)
    p = (padding,) if isinstance(padding, int) else tuple(padding)
    return prims.convolution(a, w, bias, stride=s, padding=tuple((pi, pi) for pi in p),
                             dilation=d, groups=groups)


@opsymbol
def conv3d(a, w, bias=None, stride=1, padding=0, dilation=1, groups=1):
    _tensor_like(a, "conv3d")
    a, w, bias = maybe_autocast(a, w, bias)

    def _triple(x):
        return (x, x, x) if isinstance(x, int) else tuple(x)

    s, d, p = _triple(stride), _triple(dilation), _triple(padding)
    return prims.convolution(a, w, bias, stride=s, padding=tuple((pi, pi) for pi in p),
                             dilation=d, groups=groups)


@opsymbol
def convolution(a, w, bias=None, stride=1, padding=0, dilation=1, groups=1):
    """Generic N-d convolution over the CONVOLUTION prim (spatial rank
    inferred from the input, torch ``convolution``-style int-or-sequence
    args)."""
    _tensor_like(a, "convolution")
    nd = a.ndim - 2
    check(nd >= 1, "convolution: input must have at least one spatial dim")

    def _tup(x):
        return (x,) * nd if isinstance(x, int) else tuple(x)

    s, d, p = _tup(stride), _tup(dilation), _tup(padding)
    return prims.convolution(a, w, bias, stride=s, padding=tuple((pi, pi) for pi in p),
                             dilation=d, groups=groups)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

@opsymbol
def sigmoid(a):
    a = _float_promote(a)
    return true_divide(1.0, add(1.0, exp(neg(a))))


@opsymbol
def relu(a):
    return maximum(a, zeros_like(a) if isinstance(a, TensorProxy) else 0)


@opsymbol
def silu(a):
    return mul(a, sigmoid(a))


@opsymbol
def gelu(a, approximate: str = "none"):
    a = _float_promote(a)
    if approximate == "tanh":
        inner = mul(math.sqrt(2.0 / math.pi), add(a, mul(0.044715, mul(a, mul(a, a)))))
        return mul(mul(0.5, a), add(1.0, tanh(inner)))
    return mul(mul(0.5, a), add(1.0, erf(true_divide(a, math.sqrt(2.0)))))


@opsymbol
def softplus(a, beta=1.0, threshold=20.0):
    scaled = mul(a, beta)
    soft = true_divide(log1p(exp(scaled)), beta)
    return where(gt(scaled, threshold), a, soft)


@opsymbol
def leaky_relu(a, negative_slope=0.01):
    return where(ge(a, 0), a, mul(a, negative_slope))


@opsymbol
def softmax(a, dim=-1, dtype=None):
    _tensor_like(a, "softmax")
    d = canonicalize_dim(a.ndim, dim)
    if dtype is not None:
        a = convert_element_type(a, dtype)
    x = _float_promote(a)
    m = amax(x, d, keepdim=True)
    e = exp(sub(x, m))
    return true_divide(e, sum(e, d, keepdim=True))


@opsymbol
def log_softmax(a, dim=-1, dtype=None):
    _tensor_like(a, "log_softmax")
    d = canonicalize_dim(a.ndim, dim)
    if dtype is not None:
        a = convert_element_type(a, dtype)
    x = _float_promote(a)
    m = amax(x, d, keepdim=True)
    shifted = sub(x, m)
    return sub(shifted, log(sum(exp(shifted), d, keepdim=True)))



# ---------------------------------------------------------------------------
# wider torch-surface composites (reference thunder/torch/__init__.py 276 ops;
# every op below decomposes into prims, so trace-level VJP applies for free)
# ---------------------------------------------------------------------------

def frac(a):
    return sub(a, trunc(a))


def nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    if isinstance(a, Number):
        return a
    _tensor_like(a, "nan_to_num")
    fi = dtypes.finfo(a.dtype if a.dtype.is_inexact else dtypes.float32)
    posinf = float(fi.max) if posinf is None else posinf
    neginf = float(fi.min) if neginf is None else neginf
    out = where(isnan(a), nan, a)
    out = where(logical_and(isinf(out), gt(out, 0)), posinf, out)
    return where(logical_and(isinf(out), lt(out, 0)), neginf, out)


def deg2rad(a):
    return mul(a, math.pi / 180.0)


def rad2deg(a):
    return mul(a, 180.0 / math.pi)


def sinc(a):
    # computed in f32 for low-precision inputs: the grad of sin(t)/t chains
    # through (t·cos t − sin t)/t², which catastrophically cancels near 0 in
    # bf16 (jax guards its sinc with a Taylor custom-jvp for the same reason)
    af = _float_promote(a)
    low_prec = isinstance(af, TensorProxy) and af.dtype in (dtypes.bfloat16, dtypes.float16)
    x = mul(convert_element_type(af, dtypes.float32) if low_prec else af, math.pi)
    safe = where(eq(x, 0.0), ones_like(x) if isinstance(x, TensorProxy) else 1.0, x)
    out = where(eq(x, 0.0), 1.0, true_divide(sin(safe), safe))
    return convert_element_type(out, af.dtype) if low_prec else out


def logit(a, eps=None):
    if eps is not None:
        a = clamp(a, min=eps, max=1.0 - eps)
    return log(true_divide(a, sub(1.0, a)))


def xlogy(a, b):
    safe = where(eq(a, 0.0), 1.0, b)
    return where(eq(a, 0.0), zeros_like(b) if isinstance(b, TensorProxy) else 0.0,
                 mul(a, log(safe)))


def logaddexp(a, b):
    m = maximum(a, b)
    return add(m, log1p(exp(neg(abs(sub(a, b))))))


def logaddexp2(a, b):
    m = maximum(a, b)
    return add(m, true_divide(log1p(exp2(neg(abs(sub(a, b))))), math.log(2.0)))


def hypot(a, b):
    return sqrt(add(mul(a, a), mul(b, b)))


def float_power(a, b):
    return pow(_float_promote(a), _float_promote(b))


def ldexp(a, b):
    return mul(a, exp2(b))


def heaviside(a, values):
    return where(gt(a, 0.0), ones_like(a), where(eq(a, 0.0), values, zeros_like(a)))


def square(a):
    return mul(a, a)


def positive(a):
    _tensor_like(a, "positive")
    return a


def addcmul(a, t1, t2, *, value=1.0):
    return add(a, mul(mul(t1, t2), value))


def addcdiv(a, t1, t2, *, value=1.0):
    return add(a, mul(true_divide(t1, t2), value))


# -- reductions over the wider surface --------------------------------------

def logsumexp(a, dim=None, keepdim=False):
    dims = _reduce_dims(a, dim)
    m = detach(amax(a, dim, keepdim=True))
    out = log(sum(exp(sub(a, m)), dim, keepdim=True))
    out = add(out, m)
    if not keepdim:
        for d in sorted(dims, reverse=True):
            out = squeeze(out, d)
    return out


def count_nonzero(a, dim=None):
    return sum(convert_element_type(ne(a, 0), dtypes.int64), dim)


def nansum(a, dim=None, keepdim=False):
    return sum(where(isnan(a), zeros_like(a), a), dim, keepdim)


def nanmean(a, dim=None, keepdim=False):
    valid = convert_element_type(logical_not(isnan(a)),
                                 a.dtype if a.dtype.is_inexact else dtypes.float32)
    total = sum(where(isnan(a), zeros_like(a), a), dim, keepdim)
    return true_divide(total, sum(valid, dim, keepdim))


def aminmax(a, dim=None, keepdim=False):
    return amin(a, dim, keepdim), amax(a, dim, keepdim)


def vector_norm(a, ord=2, dim=None, keepdim=False):
    if ord == 2:
        return sqrt(sum(mul(a, a), dim, keepdim))
    if ord == 1:
        return sum(abs(a), dim, keepdim)
    if ord == float("inf"):
        return amax(abs(a), dim, keepdim)
    if ord == float("-inf"):
        return amin(abs(a), dim, keepdim)
    if ord == 0:
        return convert_element_type(count_nonzero(a, dim), dtypes.float32)
    return pow(sum(pow(abs(a), ord), dim, keepdim), 1.0 / ord)


def norm(a, p=2, dim=None, keepdim=False):
    return vector_norm(a, ord=p, dim=dim, keepdim=keepdim)


def median(a, dim=-1, keepdim=False):
    """Median along ``dim`` (torch convention: lower of two middles)."""
    _tensor_like(a, "median")
    d = canonicalize_dim(a.ndim, dim)
    n = a.shape[d]
    vals = sort(a, dim=d)[0]
    idx = [slice(None)] * a.ndim
    idx[d] = (n - 1) // 2
    out = getitem(vals, tuple(idx))
    return unsqueeze(out, d) if keepdim else out


# -- additional activations ---------------------------------------------------

def relu6(a):
    return clamp(a, min=0.0, max=6.0)


def hardtanh(a, min_val=-1.0, max_val=1.0):
    return clamp(a, min=min_val, max=max_val)


def hardswish(a):
    return mul(a, true_divide(clamp(add(a, 3.0), min=0.0, max=6.0), 6.0))


def hardsigmoid(a):
    return true_divide(clamp(add(a, 3.0), min=0.0, max=6.0), 6.0)


def elu(a, alpha=1.0):
    return where(gt(a, 0.0), a, mul(alpha, expm1(a)))


def selu(a):
    _alpha = 1.6732632423543772
    _scale = 1.0507009873554805
    return mul(_scale, elu(a, _alpha))


def celu(a, alpha=1.0):
    return where(gt(a, 0.0), a, mul(alpha, expm1(true_divide(a, alpha))))


def mish(a):
    return mul(a, tanh(softplus(a)))


def softsign(a):
    return true_divide(a, add(1.0, abs(a)))


def tanhshrink(a):
    return sub(a, tanh(a))


def hardshrink(a, lambd=0.5):
    return where(gt(abs(a), lambd), a, zeros_like(a))


def softshrink(a, lambd=0.5):
    return where(gt(a, lambd), sub(a, lambd),
                 where(lt(a, -lambd), add(a, lambd), zeros_like(a)))


def log_sigmoid(a):
    # stable: -softplus(-x)
    return neg(softplus(neg(a)))


def glu(a, dim=-1):
    _tensor_like(a, "glu")
    d = canonicalize_dim(a.ndim, dim)
    check(a.shape[d] % 2 == 0, "glu: dimension size must be even")
    x, g = chunk(a, 2, dim=d)
    return mul(x, sigmoid(g))


def prelu(a, weight):
    if isinstance(weight, TensorProxy) and weight.numel > 1:
        bshape = [1] * a.ndim
        bshape[1 if a.ndim > 1 else 0] = weight.numel
        weight = reshape(weight, tuple(bshape))
    return where(gt(a, 0.0), a, mul(weight, a))


def threshold(a, threshold_value, value):
    return where(gt(a, threshold_value), a, full_like(a, value))


def softmin(a, dim=-1, dtype=None):
    return softmax(neg(a), dim=dim, dtype=dtype)


# -- additional shape ops ----------------------------------------------------

def broadcast_to(a, shape):
    _tensor_like(a, "broadcast_to")
    return expand(a, shape)


def ravel(a):
    _tensor_like(a, "ravel")
    return reshape(a, (-1,))


def unflatten(a, dim, sizes):
    _tensor_like(a, "unflatten")
    d = canonicalize_dim(a.ndim, dim)
    new_shape = tuple(a.shape[:d]) + tuple(sizes) + tuple(a.shape[d + 1:])
    return reshape(a, new_shape)


def tile(a, dims):
    """numpy/torch tile: repeat the tensor dims[i] times along each axis."""
    _tensor_like(a, "tile")
    dims = tuple(dims) if isinstance(dims, (tuple, list)) else (dims,)
    out = a
    lead = len(dims) - a.ndim
    for _ in range(max(lead, 0)):
        out = unsqueeze(out, 0)
    offset = max(-lead, 0)
    for i, r in enumerate(dims):
        r = int(r)
        if r == 0:
            # numpy/torch: zero reps yield an empty extent along that axis
            d = i + offset
            idx = tuple(slice(0, 0) if j == d else slice(None) for j in range(out.ndim))
            out = getitem(out, idx)
        elif r != 1:
            out = cat([out] * r, dim=i + offset)
    return out


def tensor_split(a, indices_or_sections, dim=0):
    _tensor_like(a, "tensor_split")
    d = canonicalize_dim(a.ndim, dim)
    n = a.shape[d]
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        base, rem = divmod(n, k)
        bounds, acc = [], 0
        for i in range(k):
            acc += base + (1 if i < rem else 0)
            bounds.append(acc)
    else:
        bounds = list(indices_or_sections) + [n]
    outs, start = [], 0
    for b in bounds:
        idx = [slice(None)] * a.ndim
        idx[d] = slice(start, b)
        outs.append(getitem(a, tuple(idx)))
        start = b
    return tuple(outs)


def atleast_1d(a):
    _tensor_like(a, "atleast_1d")
    return a if a.ndim >= 1 else unsqueeze(a, 0)


def atleast_2d(a):
    _tensor_like(a, "atleast_2d")
    a = atleast_1d(a)
    return a if a.ndim >= 2 else unsqueeze(a, 0)


def atleast_3d(a):
    _tensor_like(a, "atleast_3d")
    a = atleast_2d(a)
    return a if a.ndim >= 3 else unsqueeze(a, -1)


def hstack(tensors):
    tensors = _tensor_seq(tensors, "hstack")
    tensors = [atleast_1d(t) for t in tensors]
    return cat(tensors, dim=0 if tensors[0].ndim == 1 else 1)


def vstack(tensors):
    tensors = _tensor_seq(tensors, "vstack")
    return cat([atleast_2d(t) for t in tensors], dim=0)


def dstack(tensors):
    tensors = _tensor_seq(tensors, "dstack")
    return cat([atleast_3d(t) for t in tensors], dim=2)


def unfold(a, dim, size, step):
    """Tensor.unfold: sliding windows of ``size`` every ``step`` along
    ``dim``; the window axis becomes the LAST dim (torch semantics)."""
    _tensor_like(a, "unfold")
    d = canonicalize_dim(a.ndim, dim)
    length = int(a.shape[d])
    size, step = int(pyval(size)), int(pyval(step))
    check(0 < size <= length, lambda: f"unfold: size {size} out of range for dim of {length}")
    check(step > 0, lambda: f"unfold: step must be > 0, got {step}")
    n = (length - size) // step + 1
    windows = [narrow(a, d, i * step, size) for i in range(n)]
    return movedim(stack(windows, dim=d), d + 1, -1)


def numel(a):
    return int(a.numel)


def narrow(a, dim, start, length):
    d = canonicalize_dim(a.ndim, dim)
    start = int(pyval(start))
    length = int(pyval(length))
    if start < 0:
        start += int(a.shape[d])
    check(0 <= start and length >= 0 and start + length <= a.shape[d],
          lambda: f"narrow: [{start}, {start + length}) out of bounds for "
                  f"dim {d} of size {a.shape[d]}")
    idx = [slice(None)] * a.ndim
    idx[d] = slice(start, start + length)
    return getitem(a, tuple(idx))


def select(a, dim, index):
    _tensor_like(a, "select")
    d = canonicalize_dim(a.ndim, dim)
    idx = [slice(None)] * a.ndim
    idx[d] = int(index)
    return getitem(a, tuple(idx))


def _eye_mask(n, m, dtype):
    rows = unsqueeze(arange(0, n), 1)
    cols = unsqueeze(arange(0, m), 0)
    return convert_element_type(eq(rows, cols), dtype)


def diagonal(a, offset=0, dim1=0, dim2=1):
    """Differentiable diagonal via an eye mask + sum over dim2 (static
    shapes; XLA folds the mask multiply into the reduce)."""
    _tensor_like(a, "diagonal")
    d1 = canonicalize_dim(a.ndim, dim1)
    d2 = canonicalize_dim(a.ndim, dim2)
    n, m = a.shape[d1], a.shape[d2]
    # length of the requested diagonal
    dlen = min(n, m - offset) if offset >= 0 else min(n + offset, m)
    check(dlen > 0, lambda: f"diagonal: offset {offset} out of range for ({n},{m})")
    rows = unsqueeze(arange(0, n), 1)
    cols = unsqueeze(arange(0, m), 0)
    mask = convert_element_type(eq(add(rows, offset), cols), a.dtype)
    bshape = [1] * a.ndim
    bshape[d1], bshape[d2] = n, m
    masked = mul(a, reshape(mask, tuple(bshape)))
    summed = sum(masked, dim=d2)  # (..., n, ...) with d2 removed
    # slice the valid diagonal entries along d1
    start = max(-offset, 0)
    d1_after = d1 if d1 < d2 else d1 - 1
    idx = [slice(None)] * summed.ndim
    idx[d1_after] = slice(start, start + dlen)
    out = getitem(summed, tuple(idx))
    # torch moves the diagonal to the LAST dim
    return movedim(out, d1_after, -1)


def diag(a, diagonal_offset=0):
    _tensor_like(a, "diag")
    if a.ndim == 1:
        n = a.shape[0] + builtins_abs(diagonal_offset)
        rows = unsqueeze(arange(0, n), 1)
        cols = unsqueeze(arange(0, n), 0)
        mask = convert_element_type(eq(add(rows, diagonal_offset), cols), a.dtype)
        if diagonal_offset >= 0:
            vec = pad(a, ((diagonal_offset, n - a.shape[0] - diagonal_offset, 0),))
            return mul(mask, unsqueeze(vec, 0))
        vec = pad(a, ((0, n - a.shape[0], 0),))
        return mul(mask, unsqueeze(vec, 1))
    return diagonal(a, offset=diagonal_offset)


def builtins_abs(x):
    return x if x >= 0 else -x


# -- additional linalg -------------------------------------------------------

def mv(a, v):
    _tensor_like(a, "mv")
    return matmul(a, v)


def vdot(a, b):
    return sum(mul(a, b))


def inner(a, b):
    _tensor_like(a, "inner")
    if a.ndim == 1 and b.ndim == 1:
        return vdot(a, b)
    return prims.dot_general(a, b, contract_dims=((a.ndim - 1,), (b.ndim - 1,)))


def tensordot(a, b, dims=2):
    _tensor_like(a, "tensordot")
    if isinstance(dims, int):
        ca = tuple(range(a.ndim - dims, a.ndim))
        cb = tuple(range(dims))
    else:
        ca, cb = tuple(dims[0]), tuple(dims[1])
    return prims.dot_general(a, b, contract_dims=(ca, cb))


def addmv(a, mat, vec, *, beta=1.0, alpha=1.0):
    return add(mul(a, beta), mul(mv(mat, vec), alpha))


def cosine_similarity(a, b, dim=1, eps=1e-8):
    num = sum(mul(a, b), dim)
    na = sqrt(sum(mul(a, a), dim))
    nb = sqrt(sum(mul(b, b), dim))
    return true_divide(num, maximum(mul(na, nb), eps))


def cdist(a, b, p=2.0):
    """Pairwise distances between rows: (..., n, d) x (..., m, d) -> (..., n, m)."""
    check(p == 2.0, "cdist: only p=2 supported")
    diff = sub(unsqueeze(a, -2), unsqueeze(b, -3))
    return sqrt(clamp(sum(mul(diff, diff), -1), min=0.0))


# ---------------------------------------------------------------------------
# batch 7 (round 3): op-surface tail — searchsorted family, bincount,
# kthvalue, cross, renorm, full multinomial
# (reference: thunder/torch/__init__.py torchsymbols; VERDICT r2 item 3)
# ---------------------------------------------------------------------------

@opsymbol
def searchsorted(sorted_sequence, values, *, right=False, side=None):
    """Insertion indices that keep ``sorted_sequence`` sorted. TPU-first:
    a broadcast compare + reduction (vectorizes on the VPU, no
    data-dependent control flow) instead of binary search; indices are
    int32 (this framework's index convention — torch returns int64)."""
    if side is not None:
        check(side in ("left", "right"),
              lambda: f"searchsorted: side must be 'left' or 'right', got {side!r}")
        check(not (right and side == "left"),
              "searchsorted: side and right can't be set to opposites")
        right = side == "right"
    scalar_out = isinstance(values, Number)
    if scalar_out:
        values = full((), values,
                      dtype=dtypes.float32 if isinstance(values, float) else dtypes.int32)
    cmp_fn = le if right else lt
    if sorted_sequence.ndim == 1:
        cmp = cmp_fn(sorted_sequence, unsqueeze(values, -1))
        out = sum(convert_element_type(cmp, dtypes.int32), -1)
    else:
        check(sorted_sequence.shape[:-1] == values.shape[:-1], lambda: (
            f"searchsorted: leading dims of sorted_sequence "
            f"{tuple(sorted_sequence.shape)} and values {tuple(values.shape)} "
            f"must match"))
        cmp = cmp_fn(unsqueeze(sorted_sequence, -2), unsqueeze(values, -1))
        out = sum(convert_element_type(cmp, dtypes.int32), -1)
    return squeeze(out, -1) if scalar_out and out.ndim else out


@opsymbol
def bucketize(input, boundaries, *, right=False):
    """torch.bucketize: bucket index of each input among 1-D ``boundaries``."""
    check(boundaries.ndim == 1,
          lambda: f"bucketize: boundaries must be 1-D, got {boundaries.ndim}-D")
    return searchsorted(boundaries, input, right=right)


@opsymbol
def bincount(a, weights=None, minlength=0):
    """Count occurrences of each value in a 1-D integer tensor.

    XLA programs have static shapes, so the torch behavior (output length
    ``max(input)+1``) is data-dependent and unsupported: ``minlength`` is
    REQUIRED (> 0) and fixes the output length; values ``>= minlength``
    are dropped (same as ``jnp.bincount(..., length=minlength)``).
    TPU-first: one-hot compare + sum-reduction, not scatter."""
    check(a.ndim == 1, lambda: f"bincount: input must be 1-D, got {a.ndim}-D")
    check(a.dtype.is_int, lambda: "bincount: input must be an integer tensor")
    minlength = int(pyval(minlength))
    check(minlength > 0,
          "bincount: static shapes require minlength > 0 (the torch default "
          "output length max(input)+1 is data-dependent)")
    onehot = eq(unsqueeze(a, 1), reshape(arange(minlength), (1, minlength)))
    if weights is not None:
        check(weights.shape == a.shape,
              lambda: "bincount: weights must have the same shape as input")
        w = convert_element_type(weights, dtypes.float32) \
            if not weights.dtype.is_inexact else weights
        return sum(mul(convert_element_type(onehot, w.dtype), unsqueeze(w, 1)), 0)
    return sum(convert_element_type(onehot, dtypes.int32), 0)


@opsymbol
def kthvalue(a, k, dim=-1, keepdim=False):
    """k-th smallest value (and its index) along ``dim``; differentiable in
    ``a`` via gather-by-index (the sort itself carries no gradient)."""
    _tensor_like(a, "kthvalue")
    d = canonicalize_dim(a.ndim, dim)
    k = int(pyval(k))
    check(1 <= k <= a.shape[d],
          lambda: f"kthvalue: k={k} out of range for dim of size {a.shape[d]}")
    inds = prims.argsort(a, d, False)
    idx = narrow(inds, d, k - 1, 1)
    vals = gather(a, d, idx)
    if not keepdim:
        vals, idx = squeeze(vals, d), squeeze(idx, d)
    return vals, idx


@opsymbol
def cross(a, b, dim=None):
    """3-D cross product along ``dim`` (default: the first size-3 dim, torch
    semantics; ``linalg.cross`` passes dim=-1)."""
    if dim is None:
        dim = next((i for i, s in enumerate(a.shape) if s == 3), None)
        check(dim is not None, "cross: no dimension of size 3 found")
    d = canonicalize_dim(a.ndim, dim)
    check(a.shape[d] == 3 and b.shape[d] == 3,
          lambda: f"cross: dim {d} must have size 3 "
                  f"(got {a.shape[d]} and {b.shape[d]})")

    def comp(x, i):
        return squeeze(narrow(x, d, i, 1), d)

    a0, a1, a2 = (comp(a, i) for i in range(3))
    b0, b1, b2 = (comp(b, i) for i in range(3))
    return stack([sub(mul(a1, b2), mul(a2, b1)),
                  sub(mul(a2, b0), mul(a0, b2)),
                  sub(mul(a0, b1), mul(a1, b0))], d)


@opsymbol
def renorm(a, p, dim, maxnorm):
    """Renormalize sub-tensors along ``dim`` whose p-norm exceeds
    ``maxnorm`` (torch.renorm, incl. its 1e-7 guard epsilon)."""
    p = float(pyval(p))
    maxnorm = float(pyval(maxnorm))
    check(p > 0, lambda: f"renorm: non-positive norm degree p={p}")
    check(maxnorm >= 0, lambda: f"renorm: negative maxnorm {maxnorm}")
    d = canonicalize_dim(a.ndim, dim)
    axes = tuple(i for i in range(a.ndim) if i != d)
    norms = vector_norm(a, ord=p, dim=axes, keepdim=True)
    factor = where(gt(norms, maxnorm),
                   true_divide(maxnorm, add(norms, 1e-7)),
                   full((), 1.0, dtype=norms.dtype))
    return mul(a, convert_element_type(factor, a.dtype))


@opsymbol
def multinomial(a, num_samples, replacement=False, *, key=None):
    """Categorical sampling via the Gumbel trick — TPU-first: with
    replacement, iid Gumbel-argmax per draw; without replacement,
    Gumbel-TOP-K (one fused topk, no sequential renormalization)."""
    check(a.ndim in (1, 2),
          lambda: f"multinomial: input must be 1-D or 2-D, got {a.ndim}-D")
    n = int(pyval(num_samples))
    C = a.shape[-1]
    check(n >= 1, lambda: f"multinomial: num_samples must be >= 1, got {n}")
    logp = log(clamp(a, min=1e-30))
    if replacement:
        gshape = tuple(a.shape[:-1]) + (n, C)
        u = uniform(gshape, 1e-20, 1.0, dtype=dtypes.float32, key=key)
        g = neg(log(neg(log(u))))
        return argmax(add(unsqueeze(logp, -2), g), dim=-1)
    check(n <= C, lambda: (
        f"multinomial: cannot draw {n} samples without replacement from "
        f"{C} categories"))
    u = uniform(tuple(a.shape), 1e-20, 1.0, dtype=dtypes.float32, key=key)
    g = neg(log(neg(log(u))))
    _, idx = prims.topk(add(logp, g), n, a.ndim - 1)
    return idx


# nn composites live in ops.nn; re-export the common entry points
from thunder_tpu.ops import nn  # noqa: E402
# optimizer composites (optim.adamw_step / optim.fused_adamw) live in
# ops.optim — imported for registration so executors can claim them
from thunder_tpu.ops import optim  # noqa: E402,F401
from thunder_tpu.ops.nn import (  # noqa: E402,F401
    cross_entropy,
    dropout,
    embedding,
    layer_norm,
    mse_loss,
    one_hot,
    rms_norm,
    scaled_dot_product_attention,
)

"""Traced functional optimizers (AdamW, SGD).

Improvement over the reference: thunder never compiles the optimizer — the
litgpt benchmark steps a plain eager ``torch.optim.AdamW``
(``thunder/benchmarks/benchmark_litgpt.py``, SURVEY §3.5 note). Here the
optimizer is ordinary ops-traced code, so ``jit(train_step)`` compiles
fwd+bwd+update into one XLA program (no host round-trips between bwd and
update, buffers donated).
"""

from __future__ import annotations

from typing import Any, NamedTuple

from thunder_tpu import ops
from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.pytree import tree_flatten, tree_map, tree_unflatten


def sharded_axis_of(p) -> str | None:
    """The mesh axis over which proxy ``p`` holds a DISTINCT shard (so its
    per-rank sum-of-squares must be psum'd over exactly that axis for a
    global norm), or ``None`` for replicated/unannotated leaves (identical
    on every rank: summing locally is already global). Shared by
    :func:`clip_grad_norm` and the numerics guard's grad-norm health
    reduction so the two cannot diverge."""
    from thunder_tpu.core.proxies import DistParallelType

    if getattr(p, "distparallel_type", None) in (
            DistParallelType.FULLY_SHARDED, DistParallelType.EXPERT_SHARDED,
            DistParallelType.COLUMN_WISE, DistParallelType.ROW_WISE):
        return getattr(p, "dist_axis", None)
    return None


def clip_grad_norm(grads, max_norm, *, params=None, eps: float = 1e-6):
    """Global-norm gradient clipping over a grad pytree, in-graph.

    Returns ``(clipped_grads, global_norm)``. The norm is the L2 norm over
    every float leaf (accumulated in f32); when it exceeds ``max_norm``
    every grad is scaled by ``max_norm / (norm + eps)`` — torch
    ``clip_grad_norm_`` semantics, but traced, so ``jit(train_step)``
    compiles it into the step (the same fused reduction shape the numerics
    sentinel uses for its grad-norm health word).

    **Distributed-aware:** pass ``params=`` (the step's parameter pytree,
    leaf-parallel with ``grads``) and leaves whose parameters are sharded
    (FSDP/ZeRO ``FULLY_SHARDED``, tensor-parallel ``COLUMN_WISE`` /
    ``ROW_WISE``, ``EXPERT_SHARDED``) contribute a *local* sum of squares
    that is all-reduced over their mesh axis before the sqrt — each rank
    clips by the TRUE global norm, not its shard's. Replicated leaves
    (DDP grads after their all-reduce) are summed locally only.
    """
    gleaves, tdef = tree_flatten(grads)
    refs = gleaves
    if params is not None:
        pleaves, _ = tree_flatten(params)
        check(len(pleaves) == len(gleaves), lambda: (
            f"clip_grad_norm: params ({len(pleaves)} leaves) is not "
            f"leaf-parallel with grads ({len(gleaves)} leaves)"))
        refs = pleaves
    f32 = dtypes.float32
    local = ops.full((), 0.0, dtype=f32)
    shared: dict[str, Any] = {}  # mesh axis -> sharded sum-of-squares
    for g, r in zip(gleaves, refs):
        if g is None or not hasattr(g, "dtype"):
            continue
        gf = ops.convert_element_type(g, f32)
        ss = ops.sum(ops.mul(gf, gf))
        axis = sharded_axis_of(r)
        if axis is None:
            local = ops.add(local, ss)
        else:
            shared[axis] = ss if axis not in shared else ops.add(shared[axis], ss)
    total = local
    if shared:
        from thunder_tpu.distributed import prims as dist_prims

        for axis in sorted(shared):
            total = ops.add(total, dist_prims.wait(
                dist_prims.all_reduce(shared[axis], axis, "sum")))
    norm = ops.sqrt(total)
    scale = ops.clamp(ops.true_divide(float(max_norm), ops.add(norm, eps)), max=1.0)

    def clip(g):
        if g is None or not hasattr(g, "dtype"):
            return g
        return ops.convert_element_type(
            ops.mul(ops.convert_element_type(g, f32), scale), g.dtype)

    return tree_unflatten(tdef, [clip(g) for g in gleaves]), norm


class AdamW:
    """AdamW with optional reduced-precision moment state.

    ``state_dtype=dtypes.bfloat16`` stores the FIRST moment in bf16 — the
    AdamW update is bandwidth-bound on TPU (read g,p,m,v + write p,m,v:
    ~23 GB/step for a 1B-param model in f32 moments), and m tolerates bf16
    because its per-step relative change (1-beta1 = 0.1) is far above
    bf16's ULP. The SECOND moment stays f32 by default: with beta2=0.999
    its per-step relative change (~0.1%) is below bf16's half-ULP (~0.2%),
    so bf16 round-to-nearest would freeze v once gradients shrink and
    silently collapse the effective step size. Pass ``v_dtype`` explicitly
    to override. Arithmetic is always f32 (upcast, update, store rounded).
    """

    def __init__(self, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01,
                 state_dtype=dtypes.float32, v_dtype=None):
        self.lr = lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.state_dtype = state_dtype
        self.v_dtype = v_dtype if v_dtype is not None else dtypes.float32

    def init(self, params):
        import jax.numpy as jnp

        return {"m": tree_map(lambda p: jnp.zeros(p.shape, self.state_dtype.jax), params),
                "v": tree_map(lambda p: jnp.zeros(p.shape, self.v_dtype.jax), params),
                "step": jnp.zeros((), jnp.float32)}

    def update(self, params, grads, state):
        """Pure function: (params, grads, state) -> (new_params, new_state).
        Runs under tracing; bias correction uses the traced step counter.

        Each parameter's pointwise chain is emitted as ONE
        ``optim.adamw_step`` composite (decomposition identical to the
        previous inline ops), so the optimizer fusion pass
        (``core/fusion_passes.optimizer_fusion_pass``) can bucket the chains
        by dtype into multi-tensor ``optim.fused_adamw`` calls — one Pallas
        launch per bucket instead of ~#params fused chains. m/v store to the
        CONFIGURED ``state_dtype``/``v_dtype`` (re-coercing checkpoint state
        that was saved wider, as this method always did)."""
        from thunder_tpu.ops import optim as optim_ops

        step = ops.add(state["step"], 1.0)
        b1, b2 = self.beta1, self.beta2
        bc1 = ops.sub(1.0, ops.pow(ops.full((), b1, dtype=dtypes.float32), step))
        bc2 = ops.sub(1.0, ops.pow(ops.full((), b2, dtype=dtypes.float32), step))

        def upd(p, g, m, v):
            return optim_ops.adamw_step(
                p, g, m, v, bc1, bc2, lr=self.lr, beta1=b1, beta2=b2,
                eps=self.eps, weight_decay=self.weight_decay,
                state_dtype=self.state_dtype, v_dtype=self.v_dtype)

        triples = tree_map(upd, params, grads, state["m"], state["v"])
        new_params = tree_map(lambda t: t[0], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_v = tree_map(lambda t: t[2], triples, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"m": new_m, "v": new_v, "step": step}


class SGD:
    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params):
        import jax.numpy as jnp

        if self.momentum:
            return {"mom": tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)}
        return {}

    def update(self, params, grads, state):
        if not self.momentum:
            def upd(p, g):
                pf = ops.convert_element_type(p, dtypes.float32)
                gf = ops.convert_element_type(g, dtypes.float32)
                if self.weight_decay:
                    gf = ops.add(gf, ops.mul(pf, self.weight_decay))
                return ops.convert_element_type(ops.sub(pf, ops.mul(gf, self.lr)), p.dtype)

            return tree_map(upd, params, grads), state

        def upd_m(p, g, m):
            pf = ops.convert_element_type(p, dtypes.float32)
            gf = ops.convert_element_type(g, dtypes.float32)
            if self.weight_decay:
                gf = ops.add(gf, ops.mul(pf, self.weight_decay))
            m_new = ops.add(ops.mul(m, self.momentum), gf)
            return ops.convert_element_type(ops.sub(pf, ops.mul(m_new, self.lr)), p.dtype), m_new

        pairs = tree_map(upd_m, params, grads, state["mom"])
        new_p = tree_map(lambda t: t[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {"mom": new_m}

"""Traced functional optimizers (AdamW, SGD).

Improvement over the reference: thunder never compiles the optimizer — the
litgpt benchmark steps a plain eager ``torch.optim.AdamW``
(``thunder/benchmarks/benchmark_litgpt.py``, SURVEY §3.5 note). Here the
optimizer is ordinary ops-traced code, so ``jit(train_step)`` compiles
fwd+bwd+update into one XLA program (no host round-trips between bwd and
update, buffers donated).
"""

from __future__ import annotations

from typing import Any, NamedTuple

from thunder_tpu import ops
from thunder_tpu.core import dtypes
from thunder_tpu.core.pytree import tree_map


class AdamW:
    """AdamW with optional reduced-precision moment state.

    ``state_dtype=dtypes.bfloat16`` stores the FIRST moment in bf16 — the
    AdamW update is bandwidth-bound on TPU (read g,p,m,v + write p,m,v:
    ~23 GB/step for a 1B-param model in f32 moments), and m tolerates bf16
    because its per-step relative change (1-beta1 = 0.1) is far above
    bf16's ULP. The SECOND moment stays f32 by default: with beta2=0.999
    its per-step relative change (~0.1%) is below bf16's half-ULP (~0.2%),
    so bf16 round-to-nearest would freeze v once gradients shrink and
    silently collapse the effective step size. Pass ``v_dtype`` explicitly
    to override. Arithmetic is always f32 (upcast, update, store rounded).
    """

    def __init__(self, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01,
                 state_dtype=dtypes.float32, v_dtype=None):
        self.lr = lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.state_dtype = state_dtype
        self.v_dtype = v_dtype if v_dtype is not None else dtypes.float32

    def init(self, params):
        import jax.numpy as jnp

        return {"m": tree_map(lambda p: jnp.zeros(p.shape, self.state_dtype.jax), params),
                "v": tree_map(lambda p: jnp.zeros(p.shape, self.v_dtype.jax), params),
                "step": jnp.zeros((), jnp.float32)}

    def update(self, params, grads, state):
        """Pure function: (params, grads, state) -> (new_params, new_state).
        Runs under tracing; bias correction uses the traced step counter.

        Each parameter's pointwise chain is emitted as ONE
        ``optim.adamw_step`` composite (decomposition identical to the
        previous inline ops), so the optimizer fusion pass
        (``core/fusion_passes.optimizer_fusion_pass``) can bucket the chains
        by dtype into multi-tensor ``optim.fused_adamw`` calls — one Pallas
        launch per bucket instead of ~#params fused chains. m/v store to the
        CONFIGURED ``state_dtype``/``v_dtype`` (re-coercing checkpoint state
        that was saved wider, as this method always did)."""
        from thunder_tpu.ops import optim as optim_ops

        step = ops.add(state["step"], 1.0)
        b1, b2 = self.beta1, self.beta2
        bc1 = ops.sub(1.0, ops.pow(ops.full((), b1, dtype=dtypes.float32), step))
        bc2 = ops.sub(1.0, ops.pow(ops.full((), b2, dtype=dtypes.float32), step))

        def upd(p, g, m, v):
            return optim_ops.adamw_step(
                p, g, m, v, bc1, bc2, lr=self.lr, beta1=b1, beta2=b2,
                eps=self.eps, weight_decay=self.weight_decay,
                state_dtype=self.state_dtype, v_dtype=self.v_dtype)

        triples = tree_map(upd, params, grads, state["m"], state["v"])
        new_params = tree_map(lambda t: t[0], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_v = tree_map(lambda t: t[2], triples, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"m": new_m, "v": new_v, "step": step}


class SGD:
    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params):
        import jax.numpy as jnp

        if self.momentum:
            return {"mom": tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)}
        return {}

    def update(self, params, grads, state):
        if not self.momentum:
            def upd(p, g):
                pf = ops.convert_element_type(p, dtypes.float32)
                gf = ops.convert_element_type(g, dtypes.float32)
                if self.weight_decay:
                    gf = ops.add(gf, ops.mul(pf, self.weight_decay))
                return ops.convert_element_type(ops.sub(pf, ops.mul(gf, self.lr)), p.dtype)

            return tree_map(upd, params, grads), state

        def upd_m(p, g, m):
            pf = ops.convert_element_type(p, dtypes.float32)
            gf = ops.convert_element_type(g, dtypes.float32)
            if self.weight_decay:
                gf = ops.add(gf, ops.mul(pf, self.weight_decay))
            m_new = ops.add(ops.mul(m, self.momentum), gf)
            return ops.convert_element_type(ops.sub(pf, ops.mul(m_new, self.lr)), p.dtype), m_new

        pairs = tree_map(upd_m, params, grads, state["mom"])
        new_p = tree_map(lambda t: t[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {"mom": new_m}

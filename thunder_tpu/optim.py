"""Traced functional optimizers (AdamW, SGD).

Improvement over the reference: thunder never compiles the optimizer — the
litgpt benchmark steps a plain eager ``torch.optim.AdamW``
(``thunder/benchmarks/benchmark_litgpt.py``, SURVEY §3.5 note). Here the
optimizer is ordinary ops-traced code, so ``jit(train_step)`` compiles
fwd+bwd+update into one XLA program (no host round-trips between bwd and
update, buffers donated).
"""

from __future__ import annotations

from typing import Any, NamedTuple

from thunder_tpu import ops
from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.pytree import tree_flatten, tree_map, tree_unflatten


def sharded_axis_of(p) -> str | None:
    """The mesh axis over which proxy ``p`` holds a DISTINCT shard (so its
    per-rank sum-of-squares must be psum'd over exactly that axis for a
    global norm), or ``None`` for replicated/unannotated leaves (identical
    on every rank: summing locally is already global). Shared by
    :func:`clip_grad_norm` and the numerics guard's grad-norm health
    reduction so the two cannot diverge."""
    from thunder_tpu.core.proxies import DistParallelType

    if getattr(p, "distparallel_type", None) in (
            DistParallelType.FULLY_SHARDED, DistParallelType.EXPERT_SHARDED,
            DistParallelType.COLUMN_WISE, DistParallelType.ROW_WISE):
        return getattr(p, "dist_axis", None)
    return None


def clip_grad_norm(grads, max_norm, *, params=None, eps: float = 1e-6):
    """Global-norm gradient clipping over a grad pytree, in-graph.

    Returns ``(clipped_grads, global_norm)``. The norm is the L2 norm over
    every float leaf (accumulated in f32); when it exceeds ``max_norm``
    every grad is scaled by ``max_norm / (norm + eps)`` — torch
    ``clip_grad_norm_`` semantics, but traced, so ``jit(train_step)``
    compiles it into the step (the same fused reduction shape the numerics
    sentinel uses for its grad-norm health word).

    **Distributed-aware:** pass ``params=`` (the step's parameter pytree,
    leaf-parallel with ``grads``) and leaves whose parameters are sharded
    (FSDP/ZeRO ``FULLY_SHARDED``, tensor-parallel ``COLUMN_WISE`` /
    ``ROW_WISE``, ``EXPERT_SHARDED``) contribute a *local* sum of squares
    that is all-reduced over their mesh axis before the sqrt — each rank
    clips by the TRUE global norm, not its shard's. Replicated leaves
    (DDP grads after their all-reduce) are summed locally only.
    """
    gleaves, tdef = tree_flatten(grads)
    refs = gleaves
    if params is not None:
        pleaves, _ = tree_flatten(params)
        check(len(pleaves) == len(gleaves), lambda: (
            f"clip_grad_norm: params ({len(pleaves)} leaves) is not "
            f"leaf-parallel with grads ({len(gleaves)} leaves)"))
        refs = pleaves
    f32 = dtypes.float32
    local = ops.full((), 0.0, dtype=f32)
    shared: dict[str, Any] = {}  # mesh axis -> sharded sum-of-squares
    for g, r in zip(gleaves, refs):
        if g is None or not hasattr(g, "dtype"):
            continue
        gf = ops.convert_element_type(g, f32)
        ss = ops.sum(ops.mul(gf, gf))
        axis = sharded_axis_of(r)
        if axis is None:
            local = ops.add(local, ss)
        else:
            shared[axis] = ss if axis not in shared else ops.add(shared[axis], ss)
    total = local
    if shared:
        from thunder_tpu.distributed import prims as dist_prims

        for axis in sorted(shared):
            total = ops.add(total, dist_prims.wait(
                dist_prims.all_reduce(shared[axis], axis, "sum")))
    norm = ops.sqrt(total)
    scale = ops.clamp(ops.true_divide(float(max_norm), ops.add(norm, eps)), max=1.0)

    def clip(g):
        if g is None or not hasattr(g, "dtype"):
            return g
        return ops.convert_element_type(
            ops.mul(ops.convert_element_type(g, f32), scale), g.dtype)

    return tree_unflatten(tdef, [clip(g) for g in gleaves]), norm


# Optimizer-state layout versions (stamped into slab-persistent state so
# checkpoints are self-describing and CheckpointManager round-trips across
# layout changes convert instead of shape-erroring):
#   0 — legacy per-parameter m/v trees (no marker field)
#   1 — per-dtype-bucket (rows, 128) slabs ("m"/"v" are dicts keyed by the
#       bucket dtype name, plus a "layout_version" scalar)
SLAB_LAYOUT_VERSION = 1


def opt_state_layout_version(state) -> int:
    """Layout version of a (possibly checkpoint-restored) optimizer state."""
    import numpy as np

    if isinstance(state, dict) and "layout_version" in state:
        return int(np.asarray(state["layout_version"]))
    return 0


def adapt_opt_state(state, *, params, opt):
    """Convert a restored optimizer state to the layout ``opt`` runs.

    A pre-slab checkpoint (per-parameter m/v trees) restores into a
    ``slab_persistent=True`` run by packing; a slab checkpoint restores into
    a non-persistent run by unpacking — both host-side, no shape errors
    either direction. Matching layouts pass through untouched."""
    have = opt_state_layout_version(state)
    want = SLAB_LAYOUT_VERSION if getattr(opt, "slab_persistent", False) else 0
    if have == want:
        return state
    check(isinstance(opt, AdamW),
          lambda: f"adapt_opt_state: layout conversion needs an AdamW "
                  f"optimizer, got {type(opt).__name__}")
    return opt.pack_state(params, state) if want == SLAB_LAYOUT_VERSION \
        else opt.unpack_state(params, state)


def _dist_annotated(p) -> bool:
    # the fusion passes' predicate, not a re-implementation: the slab
    # path's safety check and the planners' dist-annotated verdicts must
    # apply the SAME rule to the same parameter
    from thunder_tpu.core.fusion_passes import _dist_annotated as _fp_dist

    return _fp_dist(p)


class AdamW:
    """AdamW with optional reduced-precision moment state.

    ``state_dtype=dtypes.bfloat16`` stores the FIRST moment in bf16 — the
    AdamW update is bandwidth-bound on TPU (read g,p,m,v + write p,m,v:
    ~23 GB/step for a 1B-param model in f32 moments), and m tolerates bf16
    because its per-step relative change (1-beta1 = 0.1) is far above
    bf16's ULP. The SECOND moment stays f32 by default: with beta2=0.999
    its per-step relative change (~0.1%) is below bf16's half-ULP (~0.2%),
    so bf16 round-to-nearest would freeze v once gradients shrink and
    silently collapse the effective step size. Pass ``v_dtype`` explicitly
    to override. Arithmetic is always f32 (upcast, update, store rounded).

    ``slab_persistent=True`` keeps m/v packed in per-dtype-bucket
    ``(rows, 128)`` slabs BETWEEN steps: ``init`` packs once, ``update``
    emits one ``optim.fused_adamw_slab`` composite per bucket (claimed by
    the Pallas multi-tensor kernel, which reads/writes the slabs directly),
    and checkpoints save/restore the slabs with a ``layout_version`` field
    (:func:`adapt_opt_state` converts either direction). This makes the
    r6 risk note's ``pack_bytes_if_unabsorbed`` moot by construction for
    the state streams, and parameter updates stay BIT-identical to the
    pack-per-step fused path (same slab geometry, same kernel, same op
    order). Does not compose with dist-annotated (sharded) parameters —
    a slab spanning shards of different parameters has no expressible
    sharding; ``update`` raises rather than silently corrupting.
    """

    def __init__(self, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01,
                 state_dtype=dtypes.float32, v_dtype=None,
                 slab_persistent: bool = False):
        self.lr = lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.state_dtype = state_dtype
        self.v_dtype = v_dtype if v_dtype is not None else dtypes.float32
        self.slab_persistent = slab_persistent

    @staticmethod
    def _slab_layout(params):
        """Deterministic bucket layout: leaves in ``tree_flatten`` order,
        bucketed by parameter dtype name. Recomputable from any params
        pytree (concrete arrays or trace proxies), so ``init``, ``update``
        and checkpoint conversion can never disagree on slab offsets —
        that identity is load-bearing for the bit-identity contract."""
        leaves, treedef = tree_flatten(params)
        buckets: dict[str, list] = {}
        order: list[str] = []
        for i, p in enumerate(leaves):
            key = dtypes.to_dtype(p.dtype).name
            if key not in buckets:
                buckets[key] = []
                order.append(key)
            size = 1
            for d in getattr(p, "shape", ()):
                size *= int(d)
            buckets[key].append((i, tuple(getattr(p, "shape", ())), size))
        return treedef, leaves, [(k, buckets[k]) for k in order]

    def init(self, params):
        import jax.numpy as jnp

        if not self.slab_persistent:
            return {"m": tree_map(lambda p: jnp.zeros(p.shape, self.state_dtype.jax), params),
                    "v": tree_map(lambda p: jnp.zeros(p.shape, self.v_dtype.jax), params),
                    "step": jnp.zeros((), jnp.float32)}
        from thunder_tpu.ops.optim import SLAB_LANE, slab_geometry

        _, _, layout = self._slab_layout(params)
        m_slabs, v_slabs = {}, {}
        for key, members in layout:
            rows_pad, _ = slab_geometry(sum(sz for _, _, sz in members))
            m_slabs[key] = jnp.zeros((rows_pad, SLAB_LANE), self.state_dtype.jax)
            v_slabs[key] = jnp.zeros((rows_pad, SLAB_LANE), self.v_dtype.jax)
        return {"m": m_slabs, "v": v_slabs,
                "step": jnp.zeros((), jnp.float32),
                "layout_version": jnp.asarray(SLAB_LAYOUT_VERSION, jnp.int32)}

    def pack_state(self, params, state):
        """Tree-layout m/v -> slab layout (host-side; checkpoint restore
        path). Moments saved wider than the configured storage dtypes are
        re-coerced here — the same contract ``update`` applies on the first
        step of a tree-layout resume."""
        import jax.numpy as jnp

        from thunder_tpu.ops.optim import SLAB_LANE, slab_geometry

        check(opt_state_layout_version(state) == 0,
              "pack_state: state is already slab-layout")
        _, _, layout = self._slab_layout(params)
        m_leaves, _ = tree_flatten(state["m"])
        v_leaves, _ = tree_flatten(state["v"])
        m_slabs, v_slabs = {}, {}
        for key, members in layout:
            total = sum(sz for _, _, sz in members)
            rows_pad, _ = slab_geometry(total)
            n_pad = rows_pad * SLAB_LANE

            def slab(leaves, dt):
                flat = [jnp.ravel(jnp.asarray(leaves[i], dt)) for i, _, _ in members]
                cat = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
                if n_pad != total:
                    cat = jnp.concatenate([cat, jnp.zeros((n_pad - total,), dt)])
                return cat.reshape(rows_pad, SLAB_LANE)

            m_slabs[key] = slab(m_leaves, self.state_dtype.jax)
            v_slabs[key] = slab(v_leaves, self.v_dtype.jax)
        import numpy as np

        return {"m": m_slabs, "v": v_slabs,
                "step": jnp.asarray(np.asarray(state["step"]), jnp.float32),
                "layout_version": jnp.asarray(SLAB_LAYOUT_VERSION, jnp.int32)}

    def unpack_state(self, params, state):
        """Slab-layout m/v -> per-parameter trees (host-side; restoring a
        slab checkpoint into a non-persistent run)."""
        import jax.numpy as jnp

        check(opt_state_layout_version(state) == SLAB_LAYOUT_VERSION,
              "unpack_state: state is not slab-layout")
        _, leaves, layout = self._slab_layout(params)
        m_leaves = [None] * len(leaves)
        v_leaves = [None] * len(leaves)
        for key, members in layout:
            m_flat = jnp.reshape(state["m"][key], (-1,))
            v_flat = jnp.reshape(state["v"][key], (-1,))
            off = 0
            for i, shape, size in members:
                m_leaves[i] = jnp.reshape(m_flat[off:off + size], shape)
                v_leaves[i] = jnp.reshape(v_flat[off:off + size], shape)
                off += size
        treedef = tree_flatten(params)[1]
        return {"m": tree_unflatten(treedef, m_leaves),
                "v": tree_unflatten(treedef, v_leaves),
                "step": state["step"]}

    def update(self, params, grads, state):
        """Pure function: (params, grads, state) -> (new_params, new_state).
        Runs under tracing; bias correction uses the traced step counter.

        Each parameter's pointwise chain is emitted as ONE
        ``optim.adamw_step`` composite (decomposition identical to the
        previous inline ops), so the optimizer fusion pass
        (``core/fusion_passes.optimizer_fusion_pass``) can bucket the chains
        by dtype into multi-tensor ``optim.fused_adamw`` calls — one Pallas
        launch per bucket instead of ~#params fused chains. m/v store to the
        CONFIGURED ``state_dtype``/``v_dtype`` (re-coercing checkpoint state
        that was saved wider, as this method always did).

        Under ``slab_persistent=True`` the per-dtype bucketing is decided
        HERE (the layout is fixed by ``init``) and one
        ``optim.fused_adamw_slab`` composite is emitted per bucket, reading
        and writing the persistent m/v slabs directly."""
        from thunder_tpu.ops import optim as optim_ops

        if self.slab_persistent:
            return self._update_slab(params, grads, state)
        step = ops.add(state["step"], 1.0)
        b1, b2 = self.beta1, self.beta2
        bc1 = ops.sub(1.0, ops.pow(ops.full((), b1, dtype=dtypes.float32), step))
        bc2 = ops.sub(1.0, ops.pow(ops.full((), b2, dtype=dtypes.float32), step))

        def upd(p, g, m, v):
            return optim_ops.adamw_step(
                p, g, m, v, bc1, bc2, lr=self.lr, beta1=b1, beta2=b2,
                eps=self.eps, weight_decay=self.weight_decay,
                state_dtype=self.state_dtype, v_dtype=self.v_dtype)

        triples = tree_map(upd, params, grads, state["m"], state["v"])
        new_params = tree_map(lambda t: t[0], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], triples, is_leaf=lambda x: isinstance(x, tuple))
        new_v = tree_map(lambda t: t[2], triples, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"m": new_m, "v": new_v, "step": step}

    def _update_slab(self, params, grads, state):
        from thunder_tpu.core import cost_model
        from thunder_tpu.observe import decisions as _decisions
        from thunder_tpu.observe import registry as _observe
        from thunder_tpu.ops import optim as optim_ops

        check(isinstance(state, dict) and "layout_version" in state,
              "slab-persistent AdamW got a tree-layout state; convert the "
              "restored checkpoint with optim.adapt_opt_state(state, "
              "params=params, opt=opt) first")
        step = ops.add(state["step"], 1.0)
        b1, b2 = self.beta1, self.beta2
        bc1 = ops.sub(1.0, ops.pow(ops.full((), b1, dtype=dtypes.float32), step))
        bc2 = ops.sub(1.0, ops.pow(ops.full((), b2, dtype=dtypes.float32), step))
        treedef, pleaves, layout = self._slab_layout(params)
        gleaves, _ = tree_flatten(grads)
        check(len(gleaves) == len(pleaves),
              lambda: f"slab AdamW: grads ({len(gleaves)} leaves) not "
                      f"leaf-parallel with params ({len(pleaves)})")
        new_leaves = [None] * len(pleaves)
        new_m, new_v = {}, {}
        for key, members in layout:
            idxs = [i for i, _, _ in members]
            ps = tuple(pleaves[i] for i in idxs)
            gs = tuple(gleaves[i] for i in idxs)
            sizes = tuple(sz for _, _, sz in members)
            for p in ps:
                check(not _dist_annotated(p), lambda p=p: (
                    f"slab-persistent AdamW: parameter {getattr(p, 'name', p)} "
                    f"is dist-annotated — a slab spanning shards of different "
                    f"parameters has no expressible sharding; use "
                    f"slab_persistent=False under FSDP/TP"))
            check(len({dtypes.to_dtype(g.dtype).name for g in gs}) == 1,
                  lambda: "slab AdamW: mixed grad dtypes inside one "
                          "parameter-dtype bucket")
            check(key in state["m"] and key in state["v"],
                  lambda: f"slab AdamW: state has no slab for dtype bucket "
                          f"{key!r} (params changed since init?)")
            total_bytes = sum(
                cost_model.tensor_bytes(g) + 2 * (
                    cost_model.tensor_bytes(p)
                    + sz * self.state_dtype.bytes + sz * self.v_dtype.bytes)
                for p, g, sz in zip(ps, gs, sizes))
            cost = dict(cost_model.fused_adamw_cost(len(ps), total_bytes,
                                                    slab_persistent=True),
                        dtypes=(key,))
            _decisions.record(
                "fusion", "optim.fused_adamw_slab", None, "bucketed",
                "slab-persistent state: m/v stay packed between steps "
                "(pack_bytes_if_unabsorbed = 0 by construction)", cost=cost)
            _observe.inc("fusion.optimizer_buckets")
            new_ps, m_slab, v_slab = optim_ops.fused_adamw_slab(
                ps, gs, state["m"][key], state["v"][key], bc1, bc2,
                sizes=sizes, lr=self.lr, beta1=b1, beta2=b2, eps=self.eps,
                weight_decay=self.weight_decay)
            for i, pn in zip(idxs, new_ps):
                new_leaves[i] = pn
            new_m[key] = m_slab
            new_v[key] = v_slab
        return tree_unflatten(treedef, new_leaves), {
            "m": new_m, "v": new_v, "step": step,
            "layout_version": state["layout_version"]}


class SGD:
    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params):
        import jax.numpy as jnp

        if self.momentum:
            return {"mom": tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)}
        return {}

    def update(self, params, grads, state):
        if not self.momentum:
            def upd(p, g):
                pf = ops.convert_element_type(p, dtypes.float32)
                gf = ops.convert_element_type(g, dtypes.float32)
                if self.weight_decay:
                    gf = ops.add(gf, ops.mul(pf, self.weight_decay))
                return ops.convert_element_type(ops.sub(pf, ops.mul(gf, self.lr)), p.dtype)

            return tree_map(upd, params, grads), state

        def upd_m(p, g, m):
            pf = ops.convert_element_type(p, dtypes.float32)
            gf = ops.convert_element_type(g, dtypes.float32)
            if self.weight_decay:
                gf = ops.add(gf, ops.mul(pf, self.weight_decay))
            m_new = ops.add(ops.mul(m, self.momentum), gf)
            return ops.convert_element_type(ops.sub(pf, ops.mul(m_new, self.lr)), p.dtype), m_new

        pairs = tree_map(upd_m, params, grads, state["mom"])
        new_p = tree_map(lambda t: t[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        new_m = tree_map(lambda t: t[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {"mom": new_m}

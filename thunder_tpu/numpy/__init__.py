"""NumPy dialect: numpy-flavored names/semantics over the same prims.

Reference parity: ``thunder/numpy/__init__.py`` (134 LoC, ``add``/``size``
only — a proof of the multi-language design). Same role, slightly wider:
numpy naming (``multiply``, ``concatenate``, axis kwargs, ``keepdims``)
resolving into the shared op surface, registered as a language context.
"""

from __future__ import annotations

from thunder_tpu import ops as _ops

__all__ = [
    "add", "subtract", "multiply", "divide", "negative", "absolute", "abs",
    "exp", "log", "sqrt", "tanh", "sum", "mean", "amax", "amin", "argmax",
    "argmin", "reshape", "transpose", "concatenate", "stack", "where",
    "matmul", "size", "zeros_like", "ones_like",
]

add = _ops.add
subtract = _ops.sub
multiply = _ops.mul
divide = _ops.true_divide
negative = _ops.neg
absolute = _ops.abs
abs = _ops.abs
exp = _ops.exp
log = _ops.log
sqrt = _ops.sqrt
tanh = _ops.tanh
matmul = _ops.matmul
reshape = _ops.reshape
stack = _ops.stack
where = _ops.where
zeros_like = _ops.zeros_like
ones_like = _ops.ones_like


def sum(a, axis=None, keepdims=False):  # noqa: A001 — numpy naming
    return _ops.sum(a, axis, keepdim=keepdims)


def mean(a, axis=None, keepdims=False):
    return _ops.mean(a, axis, keepdim=keepdims)


def amax(a, axis=None, keepdims=False):
    return _ops.amax(a, axis, keepdim=keepdims)


def amin(a, axis=None, keepdims=False):
    return _ops.amin(a, axis, keepdim=keepdims)


def argmax(a, axis=None):
    return _ops.argmax(a, axis)


def argmin(a, axis=None):
    return _ops.argmin(a, axis)


def transpose(a, axes=None):
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    return _ops.transpose(a, tuple(axes))


def concatenate(arrays, axis=0):
    return _ops.cat(list(arrays), axis)


def size(a) -> int:
    n = 1
    for d in a.shape:
        n *= int(d)
    return n


# -- numpy-specific semantics (beyond name aliasing) -------------------------

def dot(a, b):
    """numpy.dot polymorphism: scalar multiply, 1-D·1-D inner product,
    2-D matmul, N-D: sum-product over a's last axis and b's second-to-last."""
    if getattr(a, "ndim", 0) == 0 or getattr(b, "ndim", 0) == 0:
        return _ops.mul(a, b)
    if a.ndim == 1 and b.ndim == 1:
        return _ops.sum(_ops.mul(a, b))
    if b.ndim == 1:
        return _ops.matmul(a, b)
    if a.ndim == 1:
        return _ops.matmul(a, b)
    if a.ndim == 2 and b.ndim == 2:
        return _ops.matmul(a, b)
    # N-D: contract a[-1] with b[-2] (numpy semantics, NOT broadcasting matmul)
    from thunder_tpu.core import prims as _prims

    return _prims.dot_general(a, b, contract_dims=((a.ndim - 1,), (b.ndim - 2,)),
                              batch_dims=((), ()))


outer = _ops.outer
inner = _ops.inner


def var(a, axis=None, ddof=0, keepdims=False):
    """numpy default ddof=0 (population variance) — torch defaults to 1."""
    return _ops.var(a, axis, correction=ddof, keepdim=keepdims)


def std(a, axis=None, ddof=0, keepdims=False):
    return _ops.sqrt(var(a, axis, ddof=ddof, keepdims=keepdims))


def clip(a, a_min=None, a_max=None):
    return _ops.clamp(a, min=a_min, max=a_max)


def expand_dims(a, axis):
    return _ops.unsqueeze(a, axis)


def squeeze(a, axis=None):
    if axis is None:
        dims = tuple(i for i, s in enumerate(a.shape) if int(s) == 1)
        return _ops.squeeze(a, dims) if dims else a
    axes = (axis,) if not isinstance(axis, (tuple, list)) else tuple(axis)
    for ax in axes:
        if int(a.shape[int(ax) % a.ndim]) != 1:
            # numpy raises here; torch silently no-ops — this is the numpy dialect
            raise ValueError(
                "cannot select an axis to squeeze out which has size not equal to one")
    return _ops.squeeze(a, axis)


def moveaxis(a, source, destination):
    src = [int(source)] if not isinstance(source, (tuple, list)) else [int(s) for s in source]
    dst = [int(destination)] if not isinstance(destination, (tuple, list)) \
        else [int(d) for d in destination]
    src = [s % a.ndim for s in src]
    dst = [d % a.ndim for d in dst]
    perm = [i for i in range(a.ndim) if i not in src]
    for d, s in sorted(zip(dst, src)):
        perm.insert(d, s)
    return _ops.transpose(a, tuple(perm))


def swapaxes(a, axis1, axis2):
    perm = list(range(a.ndim))
    perm[axis1 % a.ndim], perm[axis2 % a.ndim] = perm[axis2 % a.ndim], perm[axis1 % a.ndim]
    return _ops.transpose(a, tuple(perm))


def cumsum(a, axis=None):
    if axis is None:  # numpy flattens first
        return _ops.cumsum(_ops.reshape(a, (-1,)), 0)
    return _ops.cumsum(a, axis)


def sort(a, axis=-1):
    return _ops.sort(a, axis)[0]


def argsort(a, axis=-1):
    return _ops.argsort(a, axis)


def flip(a, axis=None):
    if axis is None:
        axis = tuple(range(a.ndim))
    return _ops.flip(a, axis)


def maximum(a, b):
    return _ops.maximum(a, b)


def minimum(a, b):
    return _ops.minimum(a, b)


power = _ops.pow
floor_divide = _ops.floor_divide
mod = _ops.remainder
sign = _ops.sign
tile = _ops.tile


def split(a, indices_or_sections, axis=0):
    """numpy.split: int -> equal sections (must divide); list -> cut points."""
    axis = axis % a.ndim
    n = int(a.shape[axis])
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        if n % k != 0:
            raise ValueError("array split does not result in an equal division")
        cuts = [i * (n // k) for i in range(1, k)]
    else:
        cuts = [int(c) for c in indices_or_sections]
    pieces = []
    start = 0
    for c in cuts + [n]:
        idx = [slice(None)] * a.ndim
        idx[axis] = slice(start, c)
        pieces.append(_ops.getitem(a, tuple(idx)))
        start = c
    return pieces


__all__ += [
    "dot", "outer", "inner", "var", "std", "clip", "expand_dims", "squeeze",
    "moveaxis", "swapaxes", "cumsum", "sort", "argsort", "flip", "maximum",
    "minimum", "power", "floor_divide", "mod", "sign", "tile", "split",
]

"""NumPy dialect: numpy-flavored names/semantics over the same prims.

Reference parity: ``thunder/numpy/__init__.py`` (134 LoC, ``add``/``size``
only — a proof of the multi-language design). Same role, slightly wider:
numpy naming (``multiply``, ``concatenate``, axis kwargs, ``keepdims``)
resolving into the shared op surface, registered as a language context.
"""

from __future__ import annotations

from thunder_tpu import ops as _ops

__all__ = [
    "add", "subtract", "multiply", "divide", "negative", "absolute", "abs",
    "exp", "log", "sqrt", "tanh", "sum", "mean", "amax", "amin", "argmax",
    "argmin", "reshape", "transpose", "concatenate", "stack", "where",
    "matmul", "size", "zeros_like", "ones_like",
]

add = _ops.add
subtract = _ops.sub
multiply = _ops.mul
divide = _ops.true_divide
negative = _ops.neg
absolute = _ops.abs
abs = _ops.abs
exp = _ops.exp
log = _ops.log
sqrt = _ops.sqrt
tanh = _ops.tanh
matmul = _ops.matmul
reshape = _ops.reshape
stack = _ops.stack
where = _ops.where
zeros_like = _ops.zeros_like
ones_like = _ops.ones_like


def sum(a, axis=None, keepdims=False):  # noqa: A001 — numpy naming
    return _ops.sum(a, axis, keepdim=keepdims)


def mean(a, axis=None, keepdims=False):
    return _ops.mean(a, axis, keepdim=keepdims)


def amax(a, axis=None, keepdims=False):
    return _ops.amax(a, axis, keepdim=keepdims)


def amin(a, axis=None, keepdims=False):
    return _ops.amin(a, axis, keepdim=keepdims)


def argmax(a, axis=None):
    return _ops.argmax(a, axis)


def argmin(a, axis=None):
    return _ops.argmin(a, axis)


def transpose(a, axes=None):
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    return _ops.transpose(a, tuple(axes))


def concatenate(arrays, axis=0):
    return _ops.cat(list(arrays), axis)


def size(a) -> int:
    n = 1
    for d in a.shape:
        n *= int(d)
    return n

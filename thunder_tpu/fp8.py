"""FP8 mixed precision with delayed scaling — the TransformerEngine analog.

Reference parity: ``thunder/executors/transformer_engineex.py`` — there,
``prims.linear`` is swapped for ``te_linear`` under fp8 autocast and the
mutable amax/scale state is synchronized by a pass stitched into the
backward trace (``_transformer_engine_bwd_fp8_meta_sync`` :585). TPU-first
re-design: **the fp8 state is explicit and functional** — a pytree the user
threads through the train step exactly like optimizer state, so the whole
step (including the delayed-scaling update) compiles into one XLA program
and sharding transforms see the state like any other input.

Usage::

    import thunder_tpu as tt
    from thunder_tpu import fp8

    state = fp8.init_state(n_slots=fp8.count_linears(loss_fn, params, batch))

    def train_step(params, opt_state, fp8_state, tokens, targets):
        with fp8.autocast(fp8_state) as ctx:
            loss, grads = tt.value_and_grad(lambda p: loss_fn(p, tokens, targets))(params)
        new_params, new_opt = opt.update(params, grads, opt_state)
        return loss, new_params, new_opt, ctx.updated_state()

With ``state=None`` (or plain ``fp8.autocast()``), scaling is just-in-time
(per-tensor amax computed in-graph) — no state to thread, slightly more
compute. Delayed scaling uses the rolling amax-history maximum, matching
TE's recipe (history window, margin).

Quantization recipe (TE default): activations/weights in e4m3 (max 448),
gradients in e5m2 (max 57344), compute in f32 accumulation via
``dot_general(..., preferred_element_type=f32)`` — on fp8-capable TPUs XLA
maps this onto native fp8 MXU ops; elsewhere it upcasts (storage stays fp8,
halving HBM traffic for weights/activations).
"""

from __future__ import annotations

from typing import Any

from thunder_tpu.core import dtypes
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.proxies import TensorProxy, Variable

E4M3_MAX = 448.0
E5M2_MAX = 57344.0

_fp8_stack: list = []


def current_fp8():
    return _fp8_stack[-1] if _fp8_stack else None


def init_state(n_slots: int, history: int = 16, amax_init: float = 1.0):
    """Per-linear-slot rolling amax history for activations and weights."""
    import jax.numpy as jnp

    return {
        "x_hist": jnp.full((n_slots, history), amax_init, jnp.float32),
        "w_hist": jnp.full((n_slots, history), amax_init, jnp.float32),
    }


def count_linears(fn, *args, **kwargs) -> int:
    """Trace ``fn`` once (throwaway) counting fp8-eligible linears."""
    import thunder_tpu as tt

    class _Counter(autocast):
        def __init__(self):
            super().__init__(None)

        def linear(self, a, w, bias):
            self._slot_for(w)
            from thunder_tpu import ops

            out = ops.prims.dot_general(a, w, contract_dims=((a.ndim - 1,), (1,)))
            return out if bias is None else ops.add(out, bias)

    ctr = _Counter()
    with ctr:  # context entry registers the substitution listener too,
        # so checkpoint/remat replays don't inflate the count
        tt.jit(fn, cache="no caching")(*args, **kwargs)
    return ctr._slot


class autocast:
    """Trace-time context: while active, eligible ``ops.linear`` calls lower
    to fp8 quantize → dot_general → dequantize with delayed (or JIT)
    scaling, and per-slot amaxes are collected for the state update."""

    def __init__(self, state: dict | None = None, *, margin: float = 0.0,
                 min_dim_multiple: int = 8):
        self.state = state
        self.margin = margin
        self.min_dim_multiple = min_dim_multiple
        self._slot = 0
        self._amaxes: dict[int, tuple] = {}  # slot -> (amax_x, amax_w); last write wins
        self._slot_by_weight: dict = {}

    def _slot_for(self, w) -> int:
        """Slot keyed by the WEIGHT proxy's identity, not a bare counter:
        replays that reuse the same proxies (tied lm_head/embedding call
        sites) land on the same slot, and replays that RENAME proxies
        (eval_trace composite emission, value_and_grad's sub-trace, the
        checkpoint recompute's pinned inputs) land on the same slot via the
        substitution-listener propagation registered in ``__enter__`` —
        this is what lets fp8 delayed scaling compose with tt.checkpoint:
        the backward's recomputed linears resolve to the forward's
        weight-keyed slots instead of allocating fresh ones."""
        v = Variable(w)
        s = self._slot_by_weight.get(v)
        if s is None:
            s = self._slot
            self._slot += 1
            self._slot_by_weight[v] = s
        return s

    def _on_substitution(self, orig, new) -> None:
        """Replay engines report proxy renames; a weight that already owns a
        slot hands it to its replacement so re-lowered linears reuse it."""
        if not isinstance(orig, TensorProxy) or not isinstance(new, TensorProxy):
            return
        s = self._slot_by_weight.get(Variable(orig))
        if s is not None:
            self._slot_by_weight.setdefault(Variable(new), s)

    def _record(self, slot: int, amax_x, amax_w) -> None:
        """Called from the ``nn.fp8_linear`` meta on every (re)trace.

        Within ONE live trace, multiple call sites sharing a slot (tied
        weights, checkpoint forward + backward recompute of the same
        linear) max-combine their amaxes so the shared history covers all
        sites; across trace passes (replays re-emit with fresh proxies)
        the newest — live — proxies win, since combining with a stale
        pass's proxies would reference dead variables. The trace is held
        and compared BY OBJECT IDENTITY (not id()): a bare int id can be
        reused by CPython after a TraceCtx is collected, which would alias
        a dead pass with a live one (advisor r3, medium)."""
        from thunder_tpu.core.trace import get_tracectx

        tctx = get_tracectx()
        prev = self._amaxes.get(slot)
        if prev is not None and prev[0] is tctx:
            from thunder_tpu import ops

            amax_x = ops.maximum(prev[1], amax_x)
            amax_w = ops.maximum(prev[2], amax_w)
        self._amaxes[slot] = (tctx, amax_x, amax_w)

    # -- context -----------------------------------------------------------
    def __enter__(self):
        from thunder_tpu.core.transforms import _subst_listeners

        self._slot = 0
        self._amaxes = {}
        self._slot_by_weight = {}
        _fp8_stack.append(self)
        _subst_listeners.append(self._on_substitution)
        return self

    def __exit__(self, *exc):
        from thunder_tpu.core.transforms import _subst_listeners

        _fp8_stack.pop()
        _subst_listeners.remove(self._on_substitution)
        return False

    # -- eligibility -------------------------------------------------------
    def eligible(self, a, w) -> bool:
        if not isinstance(a, TensorProxy) or not isinstance(w, TensorProxy):
            return False
        if w.ndim != 2 or not a.dtype.is_inexact or not w.dtype.is_inexact:
            return False
        m = self.min_dim_multiple
        return w.shape[0] % m == 0 and w.shape[1] % m == 0

    # -- the fp8 linear ----------------------------------------------------
    def linear(self, a, w, bias):
        from thunder_tpu.ops import nn

        slot = self._slot_for(w)
        if self.state is not None:
            check(slot < self.state["x_hist"].shape[0],
                  lambda: f"fp8 state has {self.state['x_hist'].shape[0]} slots but "
                          f"the program contains more linears; re-run "
                          f"init_state with n_slots=count_linears(...) on "
                          f"this exact program")
            sx = _scale_from_hist(self.state["x_hist"][slot], E4M3_MAX, self.margin)
            sw = _scale_from_hist(self.state["w_hist"][slot], E4M3_MAX, self.margin)
        else:
            sx = sw = None
        out, _, _ = nn.fp8_linear(a, w, sx, sw, bias, slot)
        return out

    # -- state update ------------------------------------------------------
    def updated_state(self):
        """New state pytree: histories shifted with this step's amaxes
        (the delayed-scaling recipe — TE's amax-history roll, computed
        in-graph instead of by a mutable sync pass)."""
        if self.state is None:
            return None
        from thunder_tpu import ops

        n = self.state["x_hist"].shape[0]
        amap = self._amaxes
        x_rows, w_rows = [], []
        for i in range(n):
            xh = self.state["x_hist"][i]
            wh = self.state["w_hist"][i]
            if i in amap:
                _tid, ax, aw = amap[i]
                xh = ops.cat([ops.reshape(ax, (1,)), xh[:-1]], 0)
                wh = ops.cat([ops.reshape(aw, (1,)), wh[:-1]], 0)
            x_rows.append(xh)
            w_rows.append(wh)
        return {"x_hist": ops.stack(x_rows, 0), "w_hist": ops.stack(w_rows, 0)}


def _scale_from_hist(hist, fmax: float, margin: float):
    from thunder_tpu import ops

    amax = ops.amax(hist, 0)
    amax = ops.maximum(amax, 1e-12)
    return ops.true_divide(fmax / (2.0 ** margin), amax)
